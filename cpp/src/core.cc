// Core runtime implementation: logging, timeline, response cache, stall
// inspector, and the background cycle loop with coordinator negotiation and
// fusion (role parity with horovod/common/{operations,controller}.cc,
// re-designed for a metadata-only control plane over an XLA data plane).
#include "hvd/core.h"

#include <algorithm>
#include <cinttypes>
#include <cstdlib>
#include <cstdarg>
#include <cstring>
#include <sstream>

#include "hvd/message.h"

namespace hvd {

double NowSec() {
  using clock = std::chrono::steady_clock;
  return std::chrono::duration<double>(clock::now().time_since_epoch()).count();
}

// ------------------------------------------------------------ logging
namespace {
std::atomic<int> g_log_level{2};
std::atomic<int> g_log_rank{0};
}  // namespace

void LogSetLevel(int level) { g_log_level = level; }
void LogSetRank(int rank) { g_log_rank = rank; }

void Log(LogLevel level, const std::string& msg) {
  if (static_cast<int>(level) < g_log_level.load()) return;
  static const char* names[] = {"TRACE", "DEBUG", "INFO", "WARN", "ERROR",
                                "FATAL"};
  std::fprintf(stderr, "[%s] [hvd rank %d] %s\n",
               names[static_cast<int>(level)], g_log_rank.load(), msg.c_str());
}

// ------------------------------------------------------------ timeline
void Timeline::Initialize(const std::string& path, int rank) {
  // session_mu_ serializes concurrent Initialize/Shutdown pairs (a
  // Shutdown mid-join must complete before the next session may touch
  // writer_/file_); mu_ covers the shared state recording threads read
  // after re-checking initialized_.
  std::lock_guard<std::mutex> sl(session_mu_);
  std::lock_guard<std::mutex> l(mu_);
  if (initialized_.load() || path.empty()) return;
  file_ = std::fopen(path.c_str(), "w");
  if (!file_) {
    HVD_LOG(kWarn, "timeline: cannot open " + path);
    return;
  }
  rank_ = rank;
  start_ = NowSec();
  std::fputs("[\n", file_);
  first_event_ = true;
  stop_ = false;
  // A restarted session must re-emit thread_name metadata into ITS file.
  tids_.clear();
  next_tid_ = 1;
  queue_.clear();
  writer_ = std::thread(&Timeline::WriterLoop, this);
  initialized_ = true;
  char buf[256];
  std::snprintf(buf, sizeof(buf),
                "{\"name\":\"process_name\",\"ph\":\"M\",\"pid\":%d,"
                "\"args\":{\"name\":\"rank %d\"}}",
                rank_, rank_);
  queue_.push_back(buf);
  cv_.notify_one();
}

void Timeline::Shutdown() {
  std::lock_guard<std::mutex> sl(session_mu_);
  {
    // Flip initialized_ first, under the lock: recorders re-check it
    // after acquiring mu_, so no event can slip in past this point and
    // leak into the next session's file.
    std::lock_guard<std::mutex> l(mu_);
    if (!initialized_.load()) return;
    initialized_ = false;
    stop_ = true;
  }
  cv_.notify_all();
  if (writer_.joinable()) writer_.join();
  std::lock_guard<std::mutex> l(mu_);
  std::fputs("\n]\n", file_);
  std::fclose(file_);
  file_ = nullptr;
  queue_.clear();
}

double Timeline::NowUs() { return (NowSec() - start_) * 1e6; }

int Timeline::Tid(const std::string& tensor) {
  auto it = tids_.find(tensor);
  if (it != tids_.end()) return it->second;
  int tid = next_tid_++;
  tids_[tensor] = tid;
  std::ostringstream os;
  os << "{\"name\":\"thread_name\",\"ph\":\"M\",\"pid\":" << rank_
     << ",\"tid\":" << tid << ",\"args\":{\"name\":\"" << tensor << "\"}}";
  queue_.push_back(os.str());
  return tid;
}

void Timeline::WriterLoop() {
  std::unique_lock<std::mutex> l(mu_);
  while (!stop_ || !queue_.empty()) {
    if (queue_.empty()) {
      cv_.wait_for(l, std::chrono::milliseconds(50));
      continue;
    }
    std::string ev = std::move(queue_.front());
    queue_.pop_front();
    l.unlock();
    if (!first_event_) std::fputs(",\n", file_);
    first_event_ = false;
    std::fputs(ev.c_str(), file_);
    l.lock();
    if (queue_.empty()) std::fflush(file_);
  }
}

namespace {
std::string DurEvent(const char* ph, int pid, int tid, double ts,
                     const std::string& name,
                     const std::string& args_json = "") {
  std::ostringstream os;
  os << "{\"name\":\"" << name << "\",\"ph\":\"" << ph << "\",\"pid\":" << pid
     << ",\"tid\":" << tid << ",\"ts\":" << ts;
  if (!args_json.empty()) os << ",\"args\":" << args_json;
  os << "}";
  return os.str();
}
}  // namespace

void Timeline::NegotiateStart(const std::string& tensor,
                              const std::string& op) {
  if (!initialized_.load()) return;  // lock-free disabled-path fast exit
  std::lock_guard<std::mutex> l(mu_);
  if (!initialized_.load()) return;  // re-check: shutdown raced us
  int tid = Tid(tensor);
  queue_.push_back(DurEvent("B", rank_, tid, NowUs(), "NEGOTIATE_" + op));
  cv_.notify_one();
}

void Timeline::NegotiateRankReady(const std::string& tensor, int rank) {
  if (!initialized_.load()) return;  // lock-free disabled-path fast exit
  std::lock_guard<std::mutex> l(mu_);
  if (!initialized_.load()) return;  // re-check: shutdown raced us
  int tid = Tid(tensor);
  std::ostringstream os;
  os << "{\"name\":\"" << rank << "\",\"ph\":\"i\",\"s\":\"t\",\"pid\":"
     << rank_ << ",\"tid\":" << tid << ",\"ts\":" << NowUs() << "}";
  queue_.push_back(os.str());
  cv_.notify_one();
}

void Timeline::NegotiateEnd(const std::string& tensor, const std::string& op) {
  if (!initialized_.load()) return;  // lock-free disabled-path fast exit
  std::lock_guard<std::mutex> l(mu_);
  if (!initialized_.load()) return;  // re-check: shutdown raced us
  int tid = Tid(tensor);
  queue_.push_back(DurEvent("E", rank_, tid, NowUs(), "NEGOTIATE_" + op));
  cv_.notify_one();
}

void Timeline::Begin(const std::string& tensor, const std::string& activity) {
  if (!initialized_.load()) return;  // lock-free disabled-path fast exit
  std::lock_guard<std::mutex> l(mu_);
  if (!initialized_.load()) return;  // re-check: shutdown raced us
  int tid = Tid(tensor);
  queue_.push_back(DurEvent("B", rank_, tid, NowUs(), activity));
  cv_.notify_one();
}

void Timeline::BeginPlan(const std::string& tensor,
                         const std::string& activity, uint64_t plan_id) {
  if (!initialized_.load()) return;  // lock-free disabled-path fast exit
  std::lock_guard<std::mutex> l(mu_);
  if (!initialized_.load()) return;  // re-check: shutdown raced us
  int tid = Tid(tensor);
  queue_.push_back(DurEvent(
      "B", rank_, tid, NowUs(), activity,
      "{\"plan\":\"hvd_plan_" + std::to_string(plan_id) + "\"}"));
  cv_.notify_one();
}

void Timeline::End(const std::string& tensor, const std::string& activity) {
  if (!initialized_.load()) return;  // lock-free disabled-path fast exit
  std::lock_guard<std::mutex> l(mu_);
  if (!initialized_.load()) return;  // re-check: shutdown raced us
  int tid = Tid(tensor);
  queue_.push_back(DurEvent("E", rank_, tid, NowUs(), activity));
  cv_.notify_one();
}

void Timeline::MarkCycle() {
  if (!initialized_.load()) return;  // lock-free disabled-path fast exit
  std::lock_guard<std::mutex> l(mu_);
  if (!initialized_.load()) return;  // re-check: shutdown raced us
  std::ostringstream os;
  os << "{\"name\":\"CYCLE\",\"ph\":\"i\",\"s\":\"g\",\"pid\":" << rank_
     << ",\"tid\":0,\"ts\":" << NowUs() << "}";
  queue_.push_back(os.str());
  cv_.notify_one();
}

// ------------------------------------------------------------ cache
std::string ResponseCache::Key(const Request& r) {
  std::ostringstream os;
  os << r.name << '|' << static_cast<int>(r.type) << '|'
     << static_cast<int>(r.dtype) << '|' << r.root_rank << '|' << r.reduce_op
     << '|' << r.prescale << '|' << r.postscale << '|' << r.process_set_id
     << '|';
  for (auto d : r.shape) os << d << ',';
  return os.str();
}

int32_t ResponseCache::Lookup(const Request& r) const {
  std::lock_guard<std::mutex> l(mu_);
  auto it = index_.find(Key(r));
  return it == index_.end() ? -1 : it->second;
}

void ResponseCache::Put(const Request& r, const Response& resp) {
  if (capacity_ == 0) return;
  std::lock_guard<std::mutex> l(mu_);
  std::string key = Key(r);
  auto it = index_.find(key);
  if (it != index_.end()) {
    entries_[it->second].response = resp;
    entries_[it->second].last_used = ++tick_;
    return;
  }
  int32_t bit;
  if (!free_bits_.empty()) {
    bit = free_bits_.back();
    free_bits_.pop_back();
  } else if (entries_.size() < capacity_) {
    bit = static_cast<int32_t>(entries_.size());
    entries_.emplace_back();
  } else {
    // Deterministic LRU eviction: last_used is only advanced by Put, which
    // runs in coordinator-dispatch order — identical on every rank — so
    // all ranks evict the same bit (the reference syncs evictions
    // explicitly; determinism-by-construction avoids that round).
    bit = 0;
    uint64_t oldest = UINT64_MAX;
    for (size_t i = 0; i < entries_.size(); ++i) {
      if (entries_[i].last_used < oldest) {
        oldest = entries_[i].last_used;
        bit = static_cast<int32_t>(i);
      }
    }
    index_.erase(entries_[bit].key);
  }
  entries_[bit] = Entry{key, r, resp, ++tick_};
  index_[key] = bit;
}

bool ResponseCache::Get(int32_t bit, Response* out) const {
  std::lock_guard<std::mutex> l(mu_);
  if (bit < 0 || static_cast<size_t>(bit) >= entries_.size()) return false;
  if (entries_[bit].key.empty()) return false;
  *out = entries_[bit].response;
  return true;
}

bool ResponseCache::GetRequest(int32_t bit, Request* out) const {
  std::lock_guard<std::mutex> l(mu_);
  if (bit < 0 || static_cast<size_t>(bit) >= entries_.size()) return false;
  if (entries_[bit].key.empty()) return false;
  *out = entries_[bit].request;
  return true;
}

void ResponseCache::Invalidate(const std::string& name) {
  std::lock_guard<std::mutex> l(mu_);
  for (size_t i = 0; i < entries_.size(); ++i) {
    auto& e = entries_[i];
    if (!e.key.empty() && e.key.compare(0, name.size() + 1, name + "|") == 0) {
      index_.erase(e.key);
      e = Entry{};
      free_bits_.push_back(static_cast<int32_t>(i));
    }
  }
}

void ResponseCache::Clear() {
  std::lock_guard<std::mutex> l(mu_);
  entries_.clear();
  index_.clear();
  free_bits_.clear();
  tick_ = 0;
}

// ------------------------------------------------------------ stall
void StallInspector::Record(const std::string& name, int rank) {
  std::lock_guard<std::mutex> l(mu_);
  auto& info = pending_[name];
  if (info.ranks.empty()) info.first_seen = NowSec();
  info.ranks.insert(rank);
}

void StallInspector::Clear(const std::string& name) {
  std::lock_guard<std::mutex> l(mu_);
  pending_.erase(name);
}

bool StallInspector::Check(int size) {
  if (warn_sec_ <= 0) return false;
  std::lock_guard<std::mutex> l(mu_);
  double now = NowSec();
  bool shutdown = false;
  std::vector<std::string> stalled;
  for (auto& [name, info] : pending_) {
    double waited = now - info.first_seen;
    if (waited > warn_sec_ && !info.warned &&
        static_cast<int>(info.ranks.size()) < size) {
      stalled.push_back(name);
      info.warned = true;
    }
    if (shutdown_sec_ > 0 && waited > shutdown_sec_ &&
        static_cast<int>(info.ranks.size()) < size) {
      shutdown = true;
    }
  }
  if (!stalled.empty()) {
    std::ostringstream os;
    os << "One or more tensors were submitted to be reduced, gathered or "
          "broadcasted by subset of ranks and are waiting for remainder of "
          "ranks for more than "
       << warn_sec_ << " seconds. Stalled ops:";
    for (auto& s : stalled) {
      // Process-set keys embed a \x1f separator (NegKey); print a
      // readable form instead of a raw control character.
      std::string shown = s;
      auto pos = shown.find('\x1f');
      if (pos != std::string::npos) shown.replace(pos, 1, " @");
      os << ' ' << shown;
    }
    HVD_LOG(kWarn, os.str());
  }
  return shutdown;
}

// ------------------------------------------------------------ core
namespace {
const char* ActivityName(ResponseType t) {
  switch (t) {
    case ResponseType::kAllreduce: return "XLA_ALLREDUCE";
    case ResponseType::kAllgather: return "XLA_ALLGATHER";
    case ResponseType::kBroadcast: return "XLA_BROADCAST";
    case ResponseType::kJoin: return "JOIN";
    case ResponseType::kAlltoall: return "XLA_ALLTOALL";
    case ResponseType::kReducescatter: return "XLA_REDUCESCATTER";
    case ResponseType::kAdasum: return "XLA_ADASUM";
    case ResponseType::kError: return "ERROR";
  }
  return "EXEC";
}
}  // namespace

Core& Core::Get() {
  static Core* core = new Core();
  return *core;
}

Status Core::Init(const CoreConfig& cfg) {
  if (initialized_.load()) return Status::OK();
  cfg_ = cfg;
  LogSetLevel(cfg.log_level);
  LogSetRank(cfg.rank);
  cache_.SetCapacity(cfg.cache_capacity);
  stall_.Configure(cfg.stall_warning_sec, cfg.stall_shutdown_sec);
  params_.Initialize(cfg.cycle_time_ms, cfg.fusion_threshold,
                     cfg.autotune_warmup_samples, cfg.autotune_steps_per_sample,
                     cfg.autotune_log[0] ? cfg.autotune_log : "");
  params_.SetEnabled(cfg.autotune != 0 && cfg.rank == 0);
  // Categorical dims: hierarchical knobs start from the env config and are
  // only explorable when a (cross, local) grid exists (the lowerings need
  // it); cache_enabled starts from cache_capacity.
  bool grid = cfg.local_size > 1 && cfg.cross_size > 1 &&
              cfg.local_size * cfg.cross_size == cfg.size;
  params_.SetCategorical(cfg.hierarchical_allreduce != 0,
                         cfg.hierarchical_allgather != 0,
                         cfg.cache_capacity > 0, grid);
  // Event-driven cycle wakeup (HOROVOD_TPU_EAGER_WAKEUP=0 restores the
  // reference's pure fixed-cadence behavior); the full fusion linger
  // defaults to half a cycle, capped at 2ms (isolated requests seal
  // after a 100us grace instead — see BackgroundLoop).
  if (const char* e = std::getenv("HOROVOD_TPU_EAGER_WAKEUP")) {
    eager_wakeup_ = std::string(e) != "0";
  }
  linger_s_ = std::min(cfg.cycle_time_ms / 1000.0 * 0.5, 2e-3);
  if (const char* e = std::getenv("HOROVOD_TPU_LINGER_US")) {
    linger_s_ = std::atof(e) * 1e-6;
  }
  // HOROVOD_TIMELINE_MARK_CYCLES gates cycle marks for the env-started
  // timeline (reference default: off; runtime start_timeline overrides
  // per session). Re-read each Init so a prior session's override never
  // leaks across re-init.
  const char* mc = std::getenv("HOROVOD_TIMELINE_MARK_CYCLES");
  timeline_mark_cycles_ =
      mc && mc[0] && std::string(mc) != "0" && std::string(mc) != "false";
  if (cfg.timeline_path[0]) timeline_.Initialize(cfg.timeline_path, cfg.rank);
  if (cfg.size > 1) {
    if (!cfg.coord_addr[0] || cfg.coord_port == 0) {
      return Status::Error(StatusCode::kInvalidArgument,
                           "multi-rank core requires coord_addr/coord_port");
    }
    transport_ = NewTcpTransport();
    Status s = transport_->Init(cfg);
    if (!s.ok()) {
      delete transport_;
      transport_ = nullptr;
      return s;
    }
  }
  shutdown_ = false;
  joined_ = false;
  {
    // Shutdown() sets wake_ to rouse the old loop; a re-init must not
    // inherit it — a stale wake fires one immediate cycle on the fresh
    // core, defeating the fixed cadence until the first real wakeup.
    // last_cycle_nreq_ likewise: a solo final cycle of the OLD world
    // would put the fresh world's first burst on the 100us solo-seal
    // path instead of the full fusion window.
    std::lock_guard<std::mutex> l(table_mu_);
    wake_ = false;
    flush_hint_ = false;
    last_cycle_nreq_ = 2;
  }
  thread_ = std::thread(&Core::BackgroundLoop, this);
  initialized_ = true;
  HVD_LOG(kDebug, "core initialized");
  return Status::OK();
}

void Core::Shutdown() {
  if (!initialized_.load()) return;
  shutdown_ = true;
  {
    std::lock_guard<std::mutex> l(table_mu_);
    wake_ = true;
  }
  wake_cv_.notify_all();
  if (thread_.joinable()) thread_.join();
  FailAll(Status::Error(StatusCode::kAborted, "Horovod has been shut down."));
  plan_cv_.notify_all();
  if (transport_) {
    transport_->Close();
    delete transport_;
    transport_ = nullptr;
  }
  timeline_.Shutdown();
  // Reset state so a subsequent Init starts clean (tests re-init).
  {
    std::lock_guard<std::mutex> l(plan_mu_);
    plans_.clear();
    inflight_.clear();
  }
  negotiating_.clear();
  joined_ranks_.clear();
  {
    std::lock_guard<std::mutex> l(ps_mu_);
    process_sets_.clear();
  }
  // The response cache MUST reset across re-init: a cache bit on the
  // wire is a compressed re-announcement, and an elastic re-formation
  // can seat a FRESH coordinator (respawned rank 0) that has no entry
  // for a survivor's bit — negotiation would hang forever. Same for the
  // grouped-collective bookkeeping, and for the stall inspector, whose
  // stale first_seen timestamps from the dead generation would
  // otherwise read as minutes-old stalls (spurious warnings, or an
  // instant stall-shutdown of the fresh world).
  cache_.Clear();
  group_poisoned_.clear();
  stall_.Reset();
  initialized_ = false;
}

Status Core::Enqueue(const Request& req, uint64_t* ticket) {
  if (!initialized_.load() || shutdown_.load()) {
    return Status::Error(StatusCode::kAborted, "core is not running");
  }
  if (req.process_set_id != 0) {
    // Fail fast locally: an unregistered set or a non-member submission
    // would otherwise hang negotiation on every member rank.
    bool known = false;
    bool member = IsProcessSetMember(req.process_set_id, cfg_.rank, &known);
    if (!known) {
      return Status::Error(
          StatusCode::kInvalidArgument,
          "process set " + std::to_string(req.process_set_id) +
              " is not registered on this rank");
    }
    if (!member) {
      return Status::Error(
          StatusCode::kInvalidArgument,
          "rank " + std::to_string(cfg_.rank) + " is not a member of "
              "process set " + std::to_string(req.process_set_id));
    }
  }
  std::lock_guard<std::mutex> l(table_mu_);
  if (table_.count(req.name)) {
    return Status::Error(
        StatusCode::kPreconditionError,
        "Requested to process a tensor with the same name as another tensor "
        "that is currently being processed: " + req.name);
  }
  uint64_t t;
  {
    std::lock_guard<std::mutex> tl(ticket_mu_);
    t = next_ticket_++;
    tickets_[t] = {static_cast<int>(StatusCode::kInProgress), ""};
  }
  table_[req.name] = Pending{req, t};
  queued_.push_back(req);
  last_enqueue_ = NowSec();
  // Event-driven wake (a TPU-build improvement over the reference, whose
  // RunLoopOnce always sleeps cycle_time between rounds): the background
  // loop wakes as soon as work exists, then lingers briefly so
  // near-simultaneous submissions (a backward pass) still fuse into one
  // negotiation round. SPMD ranks enqueue together, so all ranks wake
  // together and the whole round completes at enqueue+linger instead of
  // the next cycle boundary.
  if (eager_wakeup_) wake_cv_.notify_one();
  *ticket = t;
  return Status::OK();
}

Status Core::RegisterProcessSet(int32_t id,
                                const std::vector<int32_t>& ranks) {
  if (id == 0) {
    return Status::Error(StatusCode::kInvalidArgument,
                         "process set id 0 is the implicit global set");
  }
  if (ranks.empty()) {
    return Status::Error(StatusCode::kInvalidArgument,
                         "process set needs at least one member rank");
  }
  std::vector<int32_t> sorted = ranks;
  std::sort(sorted.begin(), sorted.end());
  if (std::unique(sorted.begin(), sorted.end()) != sorted.end() ||
      sorted.front() < 0 || sorted.back() >= cfg_.size) {
    return Status::Error(StatusCode::kInvalidArgument,
                         "process set ranks must be unique and in [0, size)");
  }
  std::lock_guard<std::mutex> l(ps_mu_);
  process_sets_[id] = std::move(sorted);
  return Status::OK();
}

Status Core::RemoveProcessSet(int32_t id) {
  std::lock_guard<std::mutex> l(ps_mu_);
  if (!process_sets_.erase(id)) {
    return Status::Error(StatusCode::kInvalidArgument,
                         "process set " + std::to_string(id) +
                             " is not registered");
  }
  return Status::OK();
}

bool Core::LookupProcessSet(int32_t id, std::vector<int32_t>* ranks) {
  std::lock_guard<std::mutex> l(ps_mu_);
  auto it = process_sets_.find(id);
  if (it == process_sets_.end()) return false;
  if (ranks) *ranks = it->second;
  return true;
}

bool Core::IsProcessSetMember(int32_t id, int32_t rank, bool* known) {
  std::lock_guard<std::mutex> l(ps_mu_);
  auto it = process_sets_.find(id);
  if (it == process_sets_.end()) {
    if (known) *known = false;
    return false;
  }
  if (known) *known = true;
  return std::binary_search(it->second.begin(), it->second.end(), rank);
}

Status Core::EnqueueJoin(uint64_t* ticket) {
  Request req;
  req.rank = cfg_.rank;
  req.type = RequestType::kJoin;
  req.name = "join." + std::to_string(cfg_.rank);
  std::lock_guard<std::mutex> l(table_mu_);
  if (joined_) {
    return Status::Error(StatusCode::kPreconditionError, "already joined");
  }
  joined_ = true;
  uint64_t t;
  {
    std::lock_guard<std::mutex> tl(ticket_mu_);
    t = next_ticket_++;
    tickets_[t] = {static_cast<int>(StatusCode::kInProgress), ""};
  }
  join_ticket_ = t;
  queued_.push_back(req);
  last_enqueue_ = NowSec();
  if (eager_wakeup_) wake_cv_.notify_one();
  *ticket = t;
  return Status::OK();
}

void Core::FlushHint() {
  {
    std::lock_guard<std::mutex> l(table_mu_);
    if (queued_.empty()) return;  // nothing pending; no cycle to hurry
    flush_hint_ = true;
    wake_ = true;
  }
  wake_cv_.notify_one();
}

Status Core::StartTimeline(const std::string& path, bool mark_cycles) {
  if (timeline_.initialized()) {
    return Status::Error(StatusCode::kPreconditionError,
                         "timeline is already active");
  }
  timeline_mark_cycles_ = mark_cycles;
  timeline_.Initialize(path, cfg_.rank);
  if (!timeline_.initialized()) {
    return Status::Error(StatusCode::kUnknownError,
                         "cannot open timeline file " + path);
  }
  return Status::OK();
}

void Core::StopTimeline() { timeline_.Shutdown(); }

int Core::NextPlan(Plan* out, int timeout_ms) {
  std::unique_lock<std::mutex> l(plan_mu_);
  if (!plan_cv_.wait_for(l, std::chrono::milliseconds(timeout_ms),
                         [&] { return !plans_.empty() || shutdown_.load(); })) {
    return 0;
  }
  if (!plans_.empty()) {
    *out = std::move(plans_.front());
    plans_.pop_front();
    return 1;
  }
  return shutdown_.load() ? -1 : 0;
}

void Core::PlanDone(uint64_t plan_id, int status_code, const std::string& error,
                    double duration_s, int64_t bytes) {
  Response resp;
  std::vector<uint64_t> plan_tickets;
  {
    std::lock_guard<std::mutex> l(plan_mu_);
    auto it = inflight_.find(plan_id);
    if (it == inflight_.end()) return;
    resp = std::move(it->second.response);
    plan_tickets = std::move(it->second.tickets);
    inflight_.erase(it);
  }
  for (const auto& name : resp.names) {
    timeline_.End(name, ActivityName(resp.type));
    stall_.Clear(name);
  }
  // Feed the autotuner with observed data-plane throughput.
  if (status_code == 0 && resp.type != ResponseType::kJoin) {
    params_.Update(bytes > 0 ? bytes : resp.total_bytes, duration_s);
  }
  // Resolve the tickets captured at dispatch time.
  std::lock_guard<std::mutex> tl(ticket_mu_);
  for (uint64_t t : plan_tickets) {
    tickets_[t] = {status_code, error};
  }
  if (resp.type == ResponseType::kJoin && join_ticket_ != 0) {
    tickets_[join_ticket_] = {status_code, error};
    join_ticket_ = 0;
  }
  ticket_cv_.notify_all();
}

int Core::TicketStatus(uint64_t ticket, std::string* error) {
  std::lock_guard<std::mutex> l(ticket_mu_);
  auto it = tickets_.find(ticket);
  // Unknown => already consumed by a prior status query: report complete.
  if (it == tickets_.end()) return 1;
  if (it->second.first == static_cast<int>(StatusCode::kInProgress)) {
    return static_cast<int>(StatusCode::kInProgress);
  }
  int code = it->second.first;
  if (error) *error = it->second.second;
  tickets_.erase(it);
  return code == 0 ? 1 : -code;  // 1 = done-ok, negative = error code
}

void Core::FailAll(const Status& s) {
  std::vector<uint64_t> to_fail;
  {
    std::lock_guard<std::mutex> l(table_mu_);
    for (auto& [name, p] : table_) to_fail.push_back(p.ticket);
    table_.clear();
    queued_.clear();
    if (join_ticket_ != 0) to_fail.push_back(join_ticket_);
    join_ticket_ = 0;
  }
  std::lock_guard<std::mutex> tl(ticket_mu_);
  for (auto t : to_fail) {
    tickets_[t] = {static_cast<int>(s.code), s.reason};
  }
  ticket_cv_.notify_all();
}

void Core::BackgroundLoop() {
  while (!shutdown_.load()) {
    double cycle_s = params_.cycle_time_ms() / 1000.0;
    bool woke_early = false;
    {
      std::unique_lock<std::mutex> l(table_mu_);
      woke_early = wake_cv_.wait_for(
          l, std::chrono::duration<double>(cycle_s),
          [&] {
            return wake_ || shutdown_.load() ||
                   (eager_wakeup_ && !queued_.empty());
          });
      wake_ = false;
    }
    if (shutdown_.load()) break;
    // Consume a pending flush hint (a synchronize() caller is already
    // blocked: everything it will submit is queued). Checked again
    // inside the grace/linger waits below — with eager wakeup the
    // common timing is enqueue-wakes-the-loop BEFORE the producer
    // reaches synchronize(), so the hint lands mid-wait and must be
    // able to cut that wait short, not leak into the next cycle.
    auto take_flush = [&]() {
      std::lock_guard<std::mutex> l(table_mu_);
      bool f = flush_hint_;
      flush_hint_ = false;
      return f;
    };
    bool flush = take_flush();
    if (woke_early && !flush && linger_s_ > 0) {
      // Quiescence-based fusion window: wait until no new submission has
      // arrived for the window (each arrival restarts it), bounded by one
      // cycle_time — a burst with gaps under the linger always fuses
      // fully, which the fixed-cadence design only guaranteed when the
      // burst happened to fit the remaining cycle phase.
      //
      // Adaptive width: a lone request with no fusion in the previous
      // cycle is the isolated-collective pattern (eager framework call,
      // latency-sensitive) — seal immediately; even a 100us grace costs
      // 3-5x that in sleep-quantum overshoot on a busy host. Bursts
      // (DistributedOptimizer gradient hooks enqueue many tensors per
      // step) get the full window: detected either by >1 request already
      // queued at wake, or by the previous cycle having fused >1 (so a
      // steady training loop keeps its fusion window from the second
      // step on; at worst the very first burst splits across cycles
      // once, which negotiation handles as stragglers).
      double window;
      {
        std::lock_guard<std::mutex> l(table_mu_);
        window = (queued_.size() <= 1 && last_cycle_nreq_ <= 1)
                     ? -1.0
                     : linger_s_;
      }
      if (window < 0) {
        // Solo grace: yield-spin up to 100us (never longer than the full
        // window — HOROVOD_TPU_LINGER_US below 100 must keep solo the
        // faster path) watching for burst companions: a producer
        // mid-burst gets the core on yield and enqueues the rest; a
        // truly lone caller is already blocked in synchronize. sleep_for
        // here would overshoot 3-5x on a busy host — the spin keeps the
        // seal tight.
        const double grace = std::min(1e-4, linger_s_);
        double start = NowSec();
        while (!shutdown_.load() && NowSec() - start < grace) {
          {
            std::lock_guard<std::mutex> l(table_mu_);
            if (flush_hint_) {
              // Producer is blocked waiting: seal now.
              flush_hint_ = false;
              break;
            }
            if (queued_.size() > 1) {
              window = linger_s_;
              break;
            }
          }
          std::this_thread::yield();
        }
      }
      double start = NowSec();
      while (window > 0 && !shutdown_.load() &&
             NowSec() - start < cycle_s) {
        double since;
        {
          std::lock_guard<std::mutex> l(table_mu_);
          if (flush_hint_) {
            // All of the burst is queued (its producer moved on to
            // synchronize): the rest of the linger buys nothing.
            flush_hint_ = false;
            break;
          }
          since = NowSec() - last_enqueue_;
        }
        if (since >= window) break;
        // Bounded slices so a flush hint landing mid-linger cuts the
        // wait within ~200us instead of sleeping the full window.
        std::this_thread::sleep_for(std::chrono::duration<double>(
            std::min(window - since, 2e-4)));
      }
    }
    RunCycleOnce();
  }
  // Propagate shutdown to peers once (send a shutdown RequestList).
  if (transport_) {
    RequestList mine;
    mine.shutdown = true;
    if (cfg_.rank == 0) {
      ResponseList rl;
      rl.shutdown = true;
      transport_->Broadcast(rl);
    } else {
      ResponseList ignored;
      transport_->Exchange(mine, &ignored);
    }
  }
}

namespace {
void SetBit(std::vector<uint8_t>& bits, int32_t b) {
  size_t byte = static_cast<size_t>(b) / 8;
  if (bits.size() <= byte) bits.resize(byte + 1, 0);
  bits[byte] |= static_cast<uint8_t>(1u << (b % 8));
}

std::vector<int32_t> BitsToList(const std::vector<uint8_t>& bits) {
  std::vector<int32_t> out;
  for (size_t i = 0; i < bits.size(); ++i) {
    for (int j = 0; j < 8; ++j) {
      if (bits[i] & (1u << j)) out.push_back(static_cast<int32_t>(i * 8 + j));
    }
  }
  return out;
}
}  // namespace

void Core::RunCycleOnce() {
  if (timeline_mark_cycles_.load()) timeline_.MarkCycle();
  RequestList mine;
  {
    std::lock_guard<std::mutex> l(table_mu_);
    mine.requests = std::move(queued_);
    queued_.clear();
    // A hint raced in for requests this cycle is about to carry; it
    // must not suppress the NEXT cycle's fusion window.
    flush_hint_ = false;
    // Burst history for the adaptive linger: only non-empty cycles count
    // (idle cadence ticks between training steps must not erase the
    // "this workload fuses" signal, or every step's burst would re-enter
    // the solo fast-seal path and serialize per-tensor).
    if (!mine.requests.empty()) last_cycle_nreq_ = mine.requests.size();
  }
  if (cache_.capacity() > 0 && params_.cache_enabled()) {
    // Response-cache fast path (reference controller.cc:157-186): an
    // already-seen request signature travels as one bit instead of the
    // full Request; the coordinator reconstructs it from its own
    // (deterministically identical) cache.
    std::vector<Request> full;
    for (auto& req : mine.requests) {
      // Grouped requests never ride the cache-bit path: a bit cannot
      // carry group membership, and the group barrier needs the full
      // request at the coordinator.
      int32_t bit = (req.type == RequestType::kJoin || req.group_id != 0)
                        ? -1
                        : cache_.Lookup(req);
      if (bit >= 0) {
        SetBit(mine.cache_bits, bit);
      } else {
        full.push_back(std::move(req));
      }
    }
    mine.requests = std::move(full);
  }
  for (auto& r : mine.requests) {
    if (r.type != RequestType::kJoin) {
      timeline_.Begin(r.name, "QUEUE");
    }
  }

  ResponseList verdict;
  if (cfg_.size == 1) {
    std::vector<RequestList> lists(1);
    lists[0] = std::move(mine);
    verdict = Coordinate(lists);
  } else if (cfg_.rank == 0) {
    std::vector<RequestList> lists;
    Status s = transport_->Gather(mine, &lists);
    if (!s.ok()) {
      HVD_LOG(kError, "control gather failed: " + s.reason);
      shutdown_ = true;
      // Fail every pending handle NOW: a waiter blocked in synchronize
      // must surface the peer loss as an error, not hang until an
      // external stall kill (elastic rollback depends on this).
      FailAll(Status::Error(StatusCode::kAborted,
                            "Horovod control plane lost a peer rank: " +
                                s.reason));
      return;
    }
    verdict = Coordinate(lists);
    s = transport_->Broadcast(verdict);
    if (!s.ok()) {
      HVD_LOG(kError, "control broadcast failed: " + s.reason);
      shutdown_ = true;
      FailAll(Status::Error(StatusCode::kAborted,
                            "Horovod control plane lost a peer rank: " +
                                s.reason));
      return;
    }
  } else {
    Status s = transport_->Exchange(mine, &verdict);
    if (!s.ok()) {
      HVD_LOG(kError, "control exchange failed: " + s.reason);
      shutdown_ = true;
      FailAll(Status::Error(StatusCode::kAborted,
                            "Horovod control plane lost the coordinator: " +
                                s.reason));
      return;
    }
    if (verdict.cycle_time_ms > 0 || verdict.fusion_threshold > 0) {
      params_.Initialize(
          verdict.cycle_time_ms > 0 ? verdict.cycle_time_ms
                                    : params_.cycle_time_ms(),
          verdict.fusion_threshold > 0 ? verdict.fusion_threshold
                                       : params_.fusion_threshold(),
          0, 0, "");
    }
    params_.ApplyFlags(verdict.tuned_flags);
  }
  if (verdict.shutdown) {
    HVD_LOG(kInfo, "shutdown requested by a peer rank");
    shutdown_ = true;
    FailAll(Status::Error(StatusCode::kAborted,
                          "Horovod has been shut down. This was caused by an "
                          "exception on one of the ranks or an attempt to use "
                          "a collective after one of the ranks finished."));
    return;
  }
  DispatchResponses(verdict);
}

namespace {
// Negotiation-map key: tensors in different process sets are different
// tensors even under the same name. Set 0 keeps the plain name so
// global-set behavior (messages, timelines, tests) is unchanged.
std::string NegKey(const Request& r) {
  return r.process_set_id == 0
             ? r.name
             : r.name + "\x1f" + "ps" + std::to_string(r.process_set_id);
}

const char* TypeName(RequestType t) {
  switch (t) {
    case RequestType::kAllreduce: return "ALLREDUCE";
    case RequestType::kAllgather: return "ALLGATHER";
    case RequestType::kBroadcast: return "BROADCAST";
    case RequestType::kJoin: return "JOIN";
    case RequestType::kAlltoall: return "ALLTOALL";
    case RequestType::kReducescatter: return "REDUCESCATTER";
    case RequestType::kAdasum: return "ADASUM";
  }
  return "OP";
}
}  // namespace

ResponseList Core::Coordinate(std::vector<RequestList>& lists) {
  ResponseList out;
  std::vector<Request> ready;
  for (size_t rank_i = 0; rank_i < lists.size(); ++rank_i) {
    auto& rl = lists[rank_i];
    for (int32_t bit : BitsToList(rl.cache_bits)) {
      Request req;
      if (cache_.GetRequest(bit, &req)) {
        req.rank = static_cast<int32_t>(rank_i);
        rl.requests.push_back(std::move(req));
      } else {
        HVD_LOG(kWarn, "rank " + std::to_string(rank_i) +
                           " announced unknown cache bit " +
                           std::to_string(bit));
      }
    }
  }
  for (auto& rl : lists) {
    if (rl.shutdown) out.shutdown = true;
    for (auto& req : rl.requests) {
      if (req.type == RequestType::kJoin) {
        joined_ranks_.insert(req.rank);
        continue;
      }
      const std::string key = NegKey(req);
      auto it = negotiating_.find(key);
      if (it == negotiating_.end()) {
        timeline_.NegotiateStart(req.name, TypeName(req.type));
        auto& neg = negotiating_[key];
        neg.request = req;
        neg.ranks.insert(req.rank);
        if (req.type == RequestType::kAllgather) {
          neg.dim0[req.rank] = req.shape.empty() ? 0 : req.shape[0];
        }
        stall_.Record(key, req.rank);
      } else {
        auto& neg = it->second;
        // Validation — reference ConstructResponse semantics: dtype, op
        // type, shape (exact for allreduce/broadcast, non-0 dims for
        // allgather), root + reduce-op consistency. Error messages name
        // the tensor AND the conflicting ranks (the first announcer vs
        // the contradicting one) so an abort is actionable without a
        // debugger on every host (docs/fault_tolerance.md).
        const Request& first = neg.request;
        // (Cross-set same-name requests can never meet here: NegKey embeds
        // the process_set_id, so they negotiate as distinct tensors.)
        auto shape_str = [](const Request& r) {
          std::string s = "[";
          for (size_t d = 0; d < r.shape.size(); ++d) {
            if (d) s += ",";
            s += std::to_string(r.shape[d]);
          }
          return s + "]";
        };
        auto ranks_str = [&](const std::string& what_first,
                             const std::string& what_req) {
          return " (rank " + std::to_string(first.rank) + " announced " +
                 what_first + ", rank " + std::to_string(req.rank) +
                 " announced " + what_req + ")";
        };
        if (req.type != first.type) {
          neg.error = true;
          neg.error_msg = "Mismatched collective operations for tensor " +
                          req.name +
                          ranks_str(TypeName(first.type), TypeName(req.type));
        } else if (req.dtype != first.dtype) {
          neg.error = true;
          neg.error_msg =
              "Mismatched data types for tensor " + req.name +
              ranks_str("dtype " +
                            std::to_string(static_cast<int>(first.dtype)),
                        "dtype " +
                            std::to_string(static_cast<int>(req.dtype)));
        } else if (req.type == RequestType::kBroadcast &&
                   req.root_rank != first.root_rank) {
          neg.error = true;
          neg.error_msg =
              "Mismatched root ranks for broadcast " + req.name +
              ranks_str("root " + std::to_string(first.root_rank),
                        "root " + std::to_string(req.root_rank));
        } else if ((req.type == RequestType::kAllreduce ||
                    req.type == RequestType::kAdasum) &&
                   req.reduce_op != first.reduce_op) {
          neg.error = true;
          neg.error_msg =
              "Mismatched reduce operations for tensor " + req.name +
              ranks_str("op " + std::to_string(first.reduce_op),
                        "op " + std::to_string(req.reduce_op));
        } else if (req.type == RequestType::kAllgather) {
          if (req.shape.size() != first.shape.size()) {
            neg.error = true;
            neg.error_msg = "Mismatched ranks for allgather " + req.name +
                            ranks_str(shape_str(first), shape_str(req));
          } else {
            for (size_t d = 1; d < req.shape.size(); ++d) {
              if (req.shape[d] != first.shape[d]) {
                neg.error = true;
                neg.error_msg =
                    "Mismatched non-first dimensions for allgather " +
                    req.name + ranks_str(shape_str(first), shape_str(req));
              }
            }
          }
        } else if (req.shape != first.shape) {
          neg.error = true;
          neg.error_msg = "Mismatched shapes for tensor " + req.name +
                          ranks_str(shape_str(first), shape_str(req));
        }
        neg.ranks.insert(req.rank);
        if (req.type == RequestType::kAllgather) {
          neg.dim0[req.rank] = req.shape.empty() ? 0 : req.shape[0];
        }
        stall_.Record(key, req.rank);
      }
      timeline_.NegotiateRankReady(req.name, req.rank);
    }
  }

  // One registry snapshot per cycle: the readiness loop and FuseAndEmit
  // below run per-tensor on the latency-critical coordinator thread and
  // must not take ps_mu_ (or copy member vectors) per entry.
  std::map<int32_t, std::vector<int32_t>> ps_snap;
  {
    std::lock_guard<std::mutex> psl(ps_mu_);
    ps_snap = process_sets_;
  }
  // Join is a global-set barrier (reference semantics): a joined rank is
  // absent from every set's counting, so a set's readiness target is its
  // non-joined membership.
  auto set_needed = [&](int32_t id, bool* known) -> int {
    if (known) *known = true;
    if (id == 0) return cfg_.size - static_cast<int>(joined_ranks_.size());
    auto it = ps_snap.find(id);
    if (it == ps_snap.end()) {
      if (known) *known = false;
      return 0;
    }
    int n = 0;
    for (int32_t r : it->second) {
      if (!joined_ranks_.count(r)) ++n;
    }
    return n;
  };
  // A tensor is ready when announced by all non-joined members of its
  // process set (reference: count == size - joined_size; per-set here).
  std::vector<std::string> ready_names;
  for (auto& [name, neg] : negotiating_) {
    bool known = true;
    int needed = set_needed(neg.request.process_set_id, &known);
    if (!known) {
      // Defensive: the enqueue-side check makes this unreachable in
      // correct use, but a race with RemoveProcessSet must surface as an
      // error, not a silent hang.
      neg.error = true;
      neg.error_msg = "process set " +
                      std::to_string(neg.request.process_set_id) +
                      " is not registered on the coordinator";
      ready_names.push_back(name);
    } else if (static_cast<int>(neg.ranks.size()) >= needed) {
      ready_names.push_back(name);
    }
  }
  // First-class groups: a grouped member is held (stays in negotiating_)
  // until every group_size member is all-ranks-ready, then the whole
  // group emits in one cycle — fusion can then pack it into one response
  // regardless of where cycle boundaries fell between member enqueues.
  // A member that failed validation poisons the group: every ready and
  // future member fails with the same message rather than deadlocking
  // the incomplete group.
  std::vector<std::string> done;
  std::set<std::string> done_set;  // guards double-emission: a member a
                                   // failing peer already pushed must not
                                   // re-enter the poison machinery when
                                   // its own ready_names turn comes.
  auto push_done = [&](const std::string& n) {
    if (done_set.insert(n).second) done.push_back(n);
  };
  for (auto& name : ready_names) {
    if (done_set.count(name)) continue;
    auto& neg = negotiating_[name];
    int64_t gid = neg.request.group_id;
    if (gid == 0) {
      push_done(name);
      continue;
    }
    auto pit = group_poisoned_.find(gid);
    if (neg.error || pit != group_poisoned_.end()) {
      if (!neg.error) {
        neg.error = true;
        neg.error_msg = pit->second.first;
      } else if (pit == group_poisoned_.end()) {
        // First failing member: poison the group and fail the members
        // already held ready.
        auto msg = "grouped collective failed: " + neg.error_msg;
        int remaining = neg.request.group_size - 1;
        auto git = group_ready_.find(gid);
        if (git != group_ready_.end()) {
          for (auto& m : git->second) {
            auto& mneg = negotiating_[m];
            mneg.error = true;
            mneg.error_msg = msg;
            push_done(m);
            --remaining;
          }
          group_ready_.erase(git);
        }
        if (remaining > 0) {
          group_poisoned_[gid] = {msg, remaining};
        }
        neg.error_msg = msg;
      }
      if (pit != group_poisoned_.end() && --pit->second.second <= 0) {
        group_poisoned_.erase(pit);
      }
      push_done(name);
      continue;
    }
    auto& members = group_ready_[gid];
    members.insert(name);
    if (static_cast<int32_t>(members.size()) >= neg.request.group_size) {
      for (auto& m : members) push_done(m);
      group_ready_.erase(gid);
    }
  }
  // Keep deterministic dispatch order across ranks: sort by name (the map
  // is ordered already, but be explicit).
  std::sort(done.begin(), done.end());
  for (auto& name : done) {
    auto& neg = negotiating_[name];
    timeline_.NegotiateEnd(neg.request.name, TypeName(neg.request.type));
    if (neg.error) {
      Response r;
      r.type = ResponseType::kError;
      // Plain tensor name (the table on member ranks is name-keyed);
      // the set id makes non-members skip the error plan.
      r.names = {neg.request.name};
      r.process_set_id = neg.request.process_set_id;
      r.error = neg.error_msg;
      out.responses.push_back(std::move(r));
    } else {
      ready.push_back(neg.request);
      if (neg.request.type == RequestType::kAllgather) {
        // Collect per-rank dim0 (ordered by rank) for displacement math.
        // With Join active, missing ranks contribute 0 rows.
        // (Stored via negotiating_ below in FuseAndEmit.)
      }
    }
    stall_.Clear(name);
  }

  FuseAndEmit(ready, &out, ps_snap);
  for (auto& name : done) negotiating_.erase(name);

  // All ranks joined => emit the JOIN barrier completion and reset.
  if (!joined_ranks_.empty() &&
      static_cast<int>(joined_ranks_.size()) >= cfg_.size) {
    Response r;
    r.type = ResponseType::kJoin;
    out.responses.push_back(std::move(r));
    joined_ranks_.clear();
  }

  if (stall_.Check(cfg_.size)) {
    HVD_LOG(kError, "stall shutdown threshold exceeded; aborting");
    out.shutdown = true;
  }

  // Autotuned knob sync (rank 0 -> workers). Keeps flowing after
  // convergence (enabled_ drops) so workers land on the PINNED best values
  // rather than the last explored point, and late plans stay consistent.
  if (cfg_.autotune != 0) {
    out.cycle_time_ms = params_.cycle_time_ms();
    out.fusion_threshold = params_.fusion_threshold();
    out.tuned_flags = params_.Flags();
  }
  return out;
}

void Core::FuseAndEmit(
    std::vector<Request>& ready, ResponseList* out,
    const std::map<int32_t, std::vector<int32_t>>& ps_snap) {
  // Greedy same-signature fusion with lookahead (reference FuseResponses):
  // allreduce/adasum responses pack up to the fusion threshold. Grouped
  // members fuse with their own group only, EXEMPT from the threshold
  // (the group explicitly requested one collective); a group whose
  // members have heterogeneous signatures emits one response per
  // signature and counts as a split (observability: grouped_splits()).
  int64_t threshold = params_.fusion_threshold();
  std::vector<bool> used(ready.size(), false);
  std::map<int64_t, int> group_responses;
  std::set<int64_t> group_fusable;
  for (size_t i = 0; i < ready.size(); ++i) {
    if (used[i]) continue;
    const Request& base = ready[i];
    const bool fusable_type = base.type == RequestType::kAllreduce ||
                              base.type == RequestType::kAdasum;
    if (base.group_id != 0) {
      ++group_responses[base.group_id];
      if (fusable_type) group_fusable.insert(base.group_id);
    }
    Response r;
    r.group_id = base.group_id;
    r.process_set_id = base.process_set_id;
    r.type = static_cast<ResponseType>(static_cast<uint8_t>(base.type));
    r.dtype = base.dtype;
    r.root_rank = base.root_rank;
    r.reduce_op = base.reduce_op;
    r.prescale = base.prescale;
    r.postscale = base.postscale;
    // Non-joined member count of this request's set: the Average divisor
    // and (for sets) the sub-mesh extent check.
    if (base.process_set_id == 0) {
      r.participants = cfg_.size - static_cast<int>(joined_ranks_.size());
    } else {
      r.participants = 0;
      auto psit = ps_snap.find(base.process_set_id);
      if (psit != ps_snap.end()) {
        for (int32_t rk : psit->second) {
          if (!joined_ranks_.count(rk)) ++r.participants;
        }
      }
    }
    r.names.push_back(base.name);
    r.entry_shapes.push_back(base.shape);
    r.total_bytes = base.ByteSize();
    if (base.type == RequestType::kAllgather) {
      // Per-rank dim0 for the executor's displacement math, ordered by
      // GLOBAL rank for the global set and by member position for a
      // process set; ranks that never submitted (Join zero-substitution)
      // gather the canonical zero tensor, so they contribute base dim0
      // rows.
      auto nit = negotiating_.find(NegKey(base));
      int64_t canonical = base.shape.empty() ? 0 : base.shape[0];
      auto psit = base.process_set_id != 0
                      ? ps_snap.find(base.process_set_id)
                      : ps_snap.end();
      if (psit != ps_snap.end()) {
        const std::vector<int32_t>& members = psit->second;
        r.rank_sizes.assign(members.size(), canonical);
        if (nit != negotiating_.end()) {
          for (auto& [rk, d0] : nit->second.dim0) {
            auto pos = std::lower_bound(members.begin(), members.end(), rk);
            if (pos != members.end() && *pos == rk) {
              r.rank_sizes[pos - members.begin()] = d0;
            }
          }
        }
      } else {
        r.rank_sizes.assign(cfg_.size, canonical);
        if (nit != negotiating_.end()) {
          for (auto& [rk, d0] : nit->second.dim0) {
            if (rk >= 0 && rk < cfg_.size) r.rank_sizes[rk] = d0;
          }
        }
      }
    }
    used[i] = true;
    if (fusable_type) {
      for (size_t j = i + 1; j < ready.size(); ++j) {
        if (used[j]) continue;
        const Request& cand = ready[j];
        if (cand.group_id != base.group_id) continue;
        if (cand.process_set_id != base.process_set_id) continue;
        if (cand.type != base.type || cand.dtype != base.dtype ||
            cand.reduce_op != base.reduce_op ||
            cand.prescale != base.prescale ||
            cand.postscale != base.postscale) {
          continue;
        }
        if (base.group_id == 0 &&
            r.total_bytes + cand.ByteSize() > threshold) {
          continue;
        }
        r.names.push_back(cand.name);
        r.entry_shapes.push_back(cand.shape);
        r.total_bytes += cand.ByteSize();
        used[j] = true;
      }
    }
    out->responses.push_back(std::move(r));
  }
  for (auto& [gid, n] : group_responses) {
    // Only allreduce/adasum groups are expected to fuse into ONE
    // response; a grouped allgather/reducescatter intentionally yields
    // one per-member plan (they only share the atomic HOLD), so multiple
    // responses there are by design, not a signature split.
    if (n > 1 && group_fusable.count(gid)) {
      grouped_splits_ += n - 1;
      HVD_LOG(kWarn, "grouped collective " + std::to_string(gid) +
                         " split into " + std::to_string(n) +
                         " responses (heterogeneous member signatures)");
    }
  }
}

void Core::DispatchResponses(const ResponseList& rl) {
  for (const auto& resp : rl.responses) {
    if (cache_.capacity() > 0) {
      if (resp.type == ResponseType::kError) {
        for (const auto& name : resp.names) cache_.Invalidate(name);
      } else if (resp.type != ResponseType::kJoin && resp.group_id == 0 &&
                 (rl.tuned_flags >= 0 ? (rl.tuned_flags & 4) != 0
                                      : params_.cache_enabled())) {
        // Gate on the DELIVERING VERDICT's flags, not live tuner state:
        // rank 0's tuner can flip cache_enabled between building the
        // verdict and dispatching it, and a Put skew would desynchronize
        // cache bit numbering across ranks.
        // Per-name (pre-fusion) entries, in dispatch order — identical on
        // all ranks, so bit numbering stays coherent without an explicit
        // eviction-sync round.
        for (size_t i = 0; i < resp.names.size(); ++i) {
          Request req;
          req.type = static_cast<RequestType>(
              static_cast<uint8_t>(resp.type));
          req.dtype = resp.dtype;
          req.root_rank = resp.root_rank;
          req.reduce_op = resp.reduce_op;
          req.prescale = resp.prescale;
          req.postscale = resp.postscale;
          req.process_set_id = resp.process_set_id;
          req.name = resp.names[i];
          if (i < resp.entry_shapes.size()) req.shape = resp.entry_shapes[i];
          Response single;
          single.type = resp.type;
          single.dtype = resp.dtype;
          single.root_rank = resp.root_rank;
          single.reduce_op = resp.reduce_op;
          single.prescale = resp.prescale;
          single.postscale = resp.postscale;
          single.process_set_id = resp.process_set_id;
          single.names = {resp.names[i]};
          single.entry_shapes = {req.shape};
          cache_.Put(req, single);
        }
      }
    }
    // Process-set plans exist only on member ranks: the sub-mesh
    // collective is executed by member processes alone (a non-member
    // joining the compiled computation would deadlock it). The cache Put
    // above MUST still run on every rank — bit numbering is kept
    // coherent by identical dispatch-order Puts on all ranks.
    if (resp.process_set_id != 0 && resp.type != ResponseType::kError) {
      // Error plans are exempt: they must reach the submitting rank even
      // when the set registry is in a bad state (e.g. the unknown-set
      // error itself), or its ticket would hang forever.
      if (!IsProcessSetMember(resp.process_set_id, cfg_.rank, nullptr)) {
        continue;
      }
    }
    // Remove entries from the local table; names this rank never submitted
    // (Join zero-substitution) stay absent — the executor fabricates zeros
    // from entry_shapes. A plan only consumes entries of ITS OWN process
    // set: names are per-set namespaces, so a set-A error response must
    // not clobber an unrelated same-named global (or set-B) tensor this
    // rank has in flight.
    std::vector<uint64_t> plan_tickets;
    {
      std::lock_guard<std::mutex> l(table_mu_);
      for (const auto& name : resp.names) {
        auto it = table_.find(name);
        if (it != table_.end() &&
            it->second.request.process_set_id == resp.process_set_id) {
          plan_tickets.push_back(it->second.ticket);
          table_.erase(it);
        }
        // Absent => Join zero-substitution (this rank never submitted).
      }
      if (resp.type == ResponseType::kJoin) joined_ = false;
    }
    if (resp.type == ResponseType::kError && plan_tickets.empty()) {
      // Error verdict for tensors this rank never submitted (it reached
      // us only because error plans bypass the membership skip so the
      // SUBMITTER always gets its failure): nothing to fail here.
      continue;
    }
    Plan p;
    {
      std::lock_guard<std::mutex> l(plan_mu_);
      p.id = next_plan_id_++;
      p.response = resp;
      p.tuned_flags = rl.tuned_flags;
      inflight_[p.id] = Inflight{resp, std::move(plan_tickets)};
      plans_.push_back(p);
    }
    for (const auto& name : resp.names) {
      timeline_.BeginPlan(name, ActivityName(resp.type), p.id);
    }
    plan_cv_.notify_one();
  }
}

}  // namespace hvd
