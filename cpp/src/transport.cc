// TCP control-plane transport: rank 0 coordinates; workers hold one
// persistent connection each. Role parity with the reference's
// Gloo-over-TCP controller (gather RequestLists to rank 0, broadcast the
// ResponseList), with length-prefixed frames of the hvd::wire codec.
#include <arpa/inet.h>
#include <netdb.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <poll.h>
#include <sys/socket.h>
#include <unistd.h>

#include <cerrno>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>

#include "hvd/core.h"
#include "hvd/message.h"

namespace hvd {

namespace {

Status Errno(const std::string& what) {
  return Status::Error(StatusCode::kUnknownError,
                       what + ": " + std::strerror(errno));
}

Status SendAll(int fd, const void* data, size_t n) {
  const char* p = static_cast<const char*>(data);
  while (n > 0) {
    ssize_t w = ::send(fd, p, n, MSG_NOSIGNAL);
    if (w <= 0) {
      if (w < 0 && errno == EINTR) continue;
      return Errno("send");
    }
    p += w;
    n -= static_cast<size_t>(w);
  }
  return Status::OK();
}

Status RecvAll(int fd, void* data, size_t n) {
  char* p = static_cast<char*>(data);
  while (n > 0) {
    ssize_t r = ::recv(fd, p, n, 0);
    if (r == 0) {
      return Status::Error(StatusCode::kAborted, "peer closed connection");
    }
    if (r < 0) {
      if (errno == EINTR) continue;
      return Errno("recv");
    }
    p += r;
    n -= static_cast<size_t>(r);
  }
  return Status::OK();
}

Status SendFrame(int fd, const std::vector<uint8_t>& payload) {
  uint32_t len = static_cast<uint32_t>(payload.size());
  Status s = SendAll(fd, &len, 4);
  if (!s.ok()) return s;
  return SendAll(fd, payload.data(), payload.size());
}

Status RecvFrame(int fd, std::vector<uint8_t>* payload) {
  uint32_t len = 0;
  Status s = RecvAll(fd, &len, 4);
  if (!s.ok()) return s;
  if (len > (256u << 20)) {
    return Status::Error(StatusCode::kUnknownError, "oversized control frame");
  }
  payload->resize(len);
  return RecvAll(fd, payload->data(), len);
}

class TcpTransport : public ControlTransport {
 public:
  Status Init(const CoreConfig& cfg) override {
    rank_ = cfg.rank;
    size_ = cfg.size;
    if (rank_ == 0) return InitServer(cfg);
    return InitClient(cfg);
  }

  Status Gather(const RequestList& mine,
                std::vector<RequestList>* all) override {
    all->assign(size_, RequestList{});
    (*all)[0] = mine;
    for (int r = 1; r < size_; ++r) {
      std::vector<uint8_t> frame;
      Status s = RecvFrame(fds_[r], &frame);
      if (!s.ok()) return s;
      if (!wire::DecodeRequestList(frame.data(), frame.size(), &(*all)[r])) {
        return Status::Error(StatusCode::kUnknownError,
                             "bad RequestList from rank " + std::to_string(r));
      }
    }
    return Status::OK();
  }

  Status Broadcast(const ResponseList& rl) override {
    std::vector<uint8_t> frame = wire::EncodeResponseList(rl);
    for (int r = 1; r < size_; ++r) {
      Status s = SendFrame(fds_[r], frame);
      if (!s.ok()) return s;
    }
    return Status::OK();
  }

  Status Exchange(const RequestList& mine, ResponseList* out) override {
    Status s = SendFrame(fd0_, wire::EncodeRequestList(mine));
    if (!s.ok()) return s;
    std::vector<uint8_t> frame;
    s = RecvFrame(fd0_, &frame);
    if (!s.ok()) return s;
    if (!wire::DecodeResponseList(frame.data(), frame.size(), out)) {
      return Status::Error(StatusCode::kUnknownError, "bad ResponseList");
    }
    return Status::OK();
  }

  void Close() override {
    for (int fd : fds_) {
      if (fd >= 0) ::close(fd);
    }
    fds_.clear();
    if (fd0_ >= 0) ::close(fd0_);
    fd0_ = -1;
    if (listen_fd_ >= 0) ::close(listen_fd_);
    listen_fd_ = -1;
  }

  ~TcpTransport() override { Close(); }

 private:
  // HOROVOD_START_TIMEOUT (reference --start-timeout) bounds both sides
  // of rendezvous: worker connect retries and rank 0's accept loop.
  static long StartTimeoutSec() {
    long timeout_s = 60;
    if (const char* e = std::getenv("HOROVOD_START_TIMEOUT")) {
      long v = std::atol(e);
      if (v > 0) timeout_s = v;
    }
    if (timeout_s > 86400) timeout_s = 86400;  // clamp: avoid overflow
    return timeout_s;
  }

  Status InitServer(const CoreConfig& cfg) {
    listen_fd_ = ::socket(AF_INET, SOCK_STREAM, 0);
    if (listen_fd_ < 0) return Errno("socket");
    int one = 1;
    ::setsockopt(listen_fd_, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
    sockaddr_in addr{};
    addr.sin_family = AF_INET;
    addr.sin_addr.s_addr = INADDR_ANY;
    addr.sin_port = htons(static_cast<uint16_t>(cfg.coord_port));
    if (::bind(listen_fd_, reinterpret_cast<sockaddr*>(&addr), sizeof(addr)) <
        0) {
      return Errno("bind");
    }
    if (::listen(listen_fd_, size_) < 0) return Errno("listen");
    fds_.assign(size_, -1);
    double deadline = NowSec() + static_cast<double>(StartTimeoutSec());
    for (int i = 1; i < size_; ++i) {
      // Bounded accept: a worker that never launches must abort the job
      // at the start timeout, not hang rank 0 forever.
      for (;;) {
        double left = deadline - NowSec();
        if (left <= 0) {
          return Status::Error(
              StatusCode::kUnknownError,
              "rendezvous timed out waiting for worker registrations "
              "(HOROVOD_START_TIMEOUT)");
        }
        pollfd pfd{listen_fd_, POLLIN, 0};
        int pr = ::poll(&pfd, 1, static_cast<int>(
            left * 1000 > 1000 ? 1000 : left * 1000));
        if (pr < 0) return Errno("poll");
        if (pr > 0 && (pfd.revents & POLLIN)) break;
      }
      int fd = ::accept(listen_fd_, nullptr, nullptr);
      if (fd < 0) return Errno("accept");
      int one2 = 1;
      ::setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one2, sizeof(one2));
      int32_t peer_rank = -1;
      Status s = RecvAll(fd, &peer_rank, 4);
      if (!s.ok()) return s;
      if (peer_rank < 1 || peer_rank >= size_ || fds_[peer_rank] != -1) {
        return Status::Error(StatusCode::kUnknownError,
                             "bad peer rank " + std::to_string(peer_rank));
      }
      fds_[peer_rank] = fd;
    }
    return Status::OK();
  }

  Status InitClient(const CoreConfig& cfg) {
    addrinfo hints{};
    hints.ai_family = AF_INET;
    hints.ai_socktype = SOCK_STREAM;
    addrinfo* res = nullptr;
    std::string port = std::to_string(cfg.coord_port);
    if (::getaddrinfo(cfg.coord_addr, port.c_str(), &hints, &res) != 0) {
      return Status::Error(StatusCode::kUnknownError,
                           std::string("getaddrinfo failed for ") +
                               cfg.coord_addr);
    }
    Status last = Status::OK();
    // Retry while rank 0 may still be starting; HOROVOD_START_TIMEOUT
    // (reference --start-timeout, default 30s there, 60s here for slow
    // container spin-up) bounds the wait.
    long timeout_s = StartTimeoutSec();
    for (long attempt = 0; attempt < timeout_s * 10; ++attempt) {
      fd0_ = ::socket(AF_INET, SOCK_STREAM, 0);
      if (fd0_ < 0) {
        last = Errno("socket");
        break;
      }
      if (::connect(fd0_, res->ai_addr, res->ai_addrlen) == 0) {
        int one = 1;
        ::setsockopt(fd0_, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
        int32_t my_rank = rank_;
        last = SendAll(fd0_, &my_rank, 4);
        ::freeaddrinfo(res);
        return last;
      }
      last = Errno("connect");
      ::close(fd0_);
      fd0_ = -1;
      ::usleep(100000);
    }
    ::freeaddrinfo(res);
    return last;
  }

  int rank_ = 0;
  int size_ = 1;
  int listen_fd_ = -1;
  int fd0_ = -1;              // worker -> rank0 connection
  std::vector<int> fds_;      // rank0: connection per worker rank
};

}  // namespace

ControlTransport* NewTcpTransport() { return new TcpTransport(); }

}  // namespace hvd
