// TensorFlow graph-native collective ops for horovod_tpu.
//
// Role parity with the reference's custom-op extension
// (`horovod/tensorflow/mpi_ops.cc:287-339`): real AsyncOpKernels so a
// `tf.function` graph executes collectives as first-class graph nodes —
// no PyFunc/EagerPyFunc hop — with the TF executor never blocked (the
// kernel enqueues and returns; completion fires the done callback from
// the runtime's executor thread).
//
// TPU-native division of labor: this kernel is control-plane only. It
// hands the host buffer to the Python-side runtime (negotiation in the
// C++ core, data plane = compiled XLA collectives) through a trampoline
// registered at import, and the runtime finishes the op through
// hvd_tf_finish() below, which allocates the output (dynamically shaped
// ops like allgather only know their shape post-negotiation, like the
// reference's post-coordination AllocateOutput) and copies the result.
//
// Built separately from libhvd_core.so because it needs the TensorFlow
// and Python headers: `make tf_ops`, or automatically on first use by
// horovod_tpu/tensorflow/graph_ops.py:_build (same recipe).

#include <Python.h>

#include <cstdio>
#include <cstring>
#include <mutex>
#include <string>
#include <unordered_map>

#include "tensorflow/core/framework/op.h"
#include "tensorflow/core/framework/op_kernel.h"
#include "tensorflow/core/framework/shape_inference.h"

using tensorflow::AsyncOpKernel;
using tensorflow::OpKernelConstruction;
using tensorflow::OpKernelContext;
using tensorflow::Tensor;
using tensorflow::TensorShape;

namespace {

// Python trampoline: called (with the GIL) as
//   trampoline(handle, kind, ptr, shape_tuple, tf_dtype, name,
//              root_rank, reduce_op, prescale, postscale,
//              group_id, group_size)
// and must arrange for hvd_tf_finish(handle, ...) to be called exactly
// once from any thread.
PyObject* g_trampoline = nullptr;

struct PendingOp {
  OpKernelContext* ctx;
  AsyncOpKernel::DoneCallback done;
  int remaining = 1;   // outputs not yet finished (grouped op: N)
  bool failed = false;
};

std::mutex g_mu;
std::unordered_map<long long, PendingOp> g_pending;
long long g_next_handle = 0;

class HvdCollectiveOp : public AsyncOpKernel {
 public:
  explicit HvdCollectiveOp(OpKernelConstruction* c, std::string kind)
      : AsyncOpKernel(c), kind_(std::move(kind)) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &tensor_name_));
    if (c->HasAttr("reduce_op")) c->GetAttr("reduce_op", &reduce_op_);
    if (c->HasAttr("root_rank")) c->GetAttr("root_rank", &root_rank_);
    if (c->HasAttr("prescale_factor")) c->GetAttr("prescale_factor", &pre_);
    if (c->HasAttr("postscale_factor")) c->GetAttr("postscale_factor", &post_);
    if (c->HasAttr("group_id")) {
      tensorflow::int64 gid = 0;
      c->GetAttr("group_id", &gid);
      group_id_ = static_cast<long long>(gid);
    }
    if (c->HasAttr("group_size")) c->GetAttr("group_size", &group_size_);
  }

  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    const Tensor& input = ctx->input(0);
    long long handle;
    {
      std::lock_guard<std::mutex> l(g_mu);
      handle = ++g_next_handle;
      g_pending[handle] = {ctx, std::move(done), 1, false};
    }
    PyGILState_STATE st = PyGILState_Ensure();
    bool ok = CallTrampoline(handle, 0, kind_.c_str(), input, tensor_name_,
                             root_rank_, reduce_op_, pre_, post_,
                             group_id_, group_size_);
    PyGILState_Release(st);
    if (!ok) FailPending(handle);
  }

  static bool CallTrampoline(long long handle, int out_index,
                             const char* kind, const Tensor& input,
                             const std::string& tensor_name, int root_rank,
                             int reduce_op, float pre, float post,
                             long long group_id, int group_size) {
    if (g_trampoline == nullptr) return false;
    PyObject* shape = PyTuple_New(input.dims());
    for (int i = 0; i < input.dims(); ++i) {
      PyTuple_SET_ITEM(shape, i, PyLong_FromLongLong(input.dim_size(i)));
    }
    PyObject* r = PyObject_CallFunction(
        g_trampoline, "LisKOisiiddLi", handle, out_index, kind,
        (unsigned long long)(uintptr_t)input.tensor_data().data(), shape,
        static_cast<int>(input.dtype()), tensor_name.c_str(), root_rank,
        reduce_op, pre, post, group_id, group_size);
    Py_DECREF(shape);
    if (r == nullptr) {
      PyErr_Print();
      return false;
    }
    Py_DECREF(r);
    return true;
  }

  static void FailPending(long long handle) {
    PendingOp p;
    {
      std::lock_guard<std::mutex> l(g_mu);
      auto it = g_pending.find(handle);
      if (it == g_pending.end()) return;
      p = std::move(it->second);
      g_pending.erase(it);
    }
    p.ctx->CtxFailure(tensorflow::errors::Internal(
        "horovod_tpu graph-op trampoline missing or raised"));
    p.done();
  }

 private:
  std::string kind_;
  std::string tensor_name_;
  int reduce_op_ = 0;
  int root_rank_ = -1;
  float pre_ = 1.0f;
  float post_ = 1.0f;
  long long group_id_ = 0;
  int group_size_ = 0;
};

#define DEFINE_KIND_KERNEL(cls, kind)                       \
  class cls : public HvdCollectiveOp {                      \
   public:                                                  \
    explicit cls(OpKernelConstruction* c)                   \
        : HvdCollectiveOp(c, kind) {}                       \
  };

class HvdGroupedAllreduceOp : public AsyncOpKernel {
 public:
  explicit HvdGroupedAllreduceOp(OpKernelConstruction* c) : AsyncOpKernel(c) {
    OP_REQUIRES_OK(c, c->GetAttr("tensor_name", &tensor_name_));
    c->GetAttr("reduce_op", &reduce_op_);
    c->GetAttr("prescale_factor", &pre_);
    c->GetAttr("postscale_factor", &post_);
    tensorflow::int64 gid = 0;
    c->GetAttr("group_id", &gid);
    group_id_ = static_cast<long long>(gid);
  }

  // ONE graph node for the whole group: members cannot be pruned apart
  // (a partially-pruned group would deadlock the coordinator's group
  // barrier waiting for members that never execute — observed with
  // per-member nodes under gradient-only tf.functions).
  void ComputeAsync(OpKernelContext* ctx, DoneCallback done) override {
    const int n = ctx->num_inputs();
    long long handle;
    {
      std::lock_guard<std::mutex> l(g_mu);
      handle = ++g_next_handle;
      g_pending[handle] = {ctx, std::move(done), n, false};
    }
    PyGILState_STATE st = PyGILState_Ensure();
    int launched = 0;
    for (; launched < n; ++launched) {
      if (!HvdCollectiveOp::CallTrampoline(
              handle, launched, "allreduce", ctx->input(launched),
              tensor_name_ + "." + std::to_string(launched), -1,
              reduce_op_, pre_, post_, group_id_, n)) {
        break;
      }
    }
    PyGILState_Release(st);
    if (launched < n) {
      // Fail the op NOW. The launched members are stranded: they carry
      // group_size=n and the coordinator holds the group until every
      // member arrives, which can never happen — so no completion
      // callback for them will ever fire (waiting on them would hang
      // forever, and none can be mid-completion either, which is what
      // makes the immediate done() safe: a grouped member only
      // completes when the whole group executes). A later runtime
      // drain delivers error callbacks whose hvd_tf_finish no-ops on
      // the erased handle.
      PendingOp done_op;
      bool fire = false;
      {
        std::lock_guard<std::mutex> l(g_mu);
        auto it = g_pending.find(handle);
        if (it != g_pending.end()) {
          done_op = std::move(it->second);
          g_pending.erase(it);
          fire = true;
        }
      }
      if (fire) {
        done_op.ctx->CtxFailure(tensorflow::errors::Internal(
            "horovod_tpu grouped trampoline failed at member " +
            std::to_string(launched) + " of " + std::to_string(n)));
        done_op.done();
      }
    }
  }

 private:
  std::string tensor_name_;
  int reduce_op_ = 1;
  float pre_ = 1.0f;
  float post_ = 1.0f;
  long long group_id_ = 0;
};

DEFINE_KIND_KERNEL(HvdAllreduceOp, "allreduce")
DEFINE_KIND_KERNEL(HvdAllgatherOp, "allgather")
DEFINE_KIND_KERNEL(HvdBroadcastOp, "broadcast")
DEFINE_KIND_KERNEL(HvdAlltoallOp, "alltoall")

using tensorflow::shape_inference::InferenceContext;

REGISTER_OP("HorovodTpuAllreduce")
    .Attr(
        "T: {float16, bfloat16, float32, float64, int32, int64, uint8, int8}")
    .Attr("tensor_name: string")
    .Attr("reduce_op: int = 1")
    .Attr("prescale_factor: float = 1.0")
    .Attr("postscale_factor: float = 1.0")
    .Attr("group_id: int = 0")
    .Attr("group_size: int = 0")
    .Input("tensor: T")
    .Output("sum: T")
    .SetShapeFn([](InferenceContext* c) {
      c->set_output(0, c->input(0));
      return tensorflow::OkStatus();
    });

REGISTER_OP("HorovodTpuGroupedAllreduce")
    .Attr("N: int >= 1")
    .Attr(
        "T: {float16, bfloat16, float32, float64, int32, int64, uint8, int8}")
    .Attr("tensor_name: string")
    .Attr("reduce_op: int = 1")
    .Attr("prescale_factor: float = 1.0")
    .Attr("postscale_factor: float = 1.0")
    .Attr("group_id: int = 0")
    .Input("tensors: N * T")
    .Output("sums: N * T")
    .SetShapeFn([](InferenceContext* c) {
      for (int i = 0; i < c->num_inputs(); ++i) {
        c->set_output(i, c->input(i));
      }
      return tensorflow::OkStatus();
    });

REGISTER_OP("HorovodTpuAllgather")
    .Attr(
        "T: {float16, bfloat16, float32, float64, int32, int64, uint8, int8}")
    .Attr("tensor_name: string")
    .Input("tensor: T")
    .Output("gathered: T")
    .SetShapeFn([](InferenceContext* c) {
      // dim 0 becomes the cross-rank concatenation; only its rank is known
      // statically (reference mpi_ops.cc shape fn does the same).
      tensorflow::shape_inference::ShapeHandle out;
      TF_RETURN_IF_ERROR(c->ReplaceDim(
          c->input(0), 0, c->UnknownDim(), &out));
      c->set_output(0, out);
      return tensorflow::OkStatus();
    });

REGISTER_OP("HorovodTpuBroadcast")
    .Attr(
        "T: {float16, bfloat16, float32, float64, int32, int64, uint8, int8}")
    .Attr("tensor_name: string")
    .Attr("root_rank: int")
    .Input("tensor: T")
    .Output("output: T")
    .SetShapeFn([](InferenceContext* c) {
      c->set_output(0, c->input(0));
      return tensorflow::OkStatus();
    });

REGISTER_OP("HorovodTpuAlltoall")
    .Attr(
        "T: {float16, bfloat16, float32, float64, int32, int64, uint8, int8}")
    .Attr("tensor_name: string")
    .Input("tensor: T")
    .Output("output: T")
    .SetShapeFn([](InferenceContext* c) {
      c->set_output(0, c->input(0));
      return tensorflow::OkStatus();
    });

REGISTER_KERNEL_BUILDER(
    Name("HorovodTpuAllreduce").Device(tensorflow::DEVICE_CPU),
    HvdAllreduceOp);
REGISTER_KERNEL_BUILDER(
    Name("HorovodTpuGroupedAllreduce").Device(tensorflow::DEVICE_CPU),
    HvdGroupedAllreduceOp);
REGISTER_KERNEL_BUILDER(
    Name("HorovodTpuAllgather").Device(tensorflow::DEVICE_CPU),
    HvdAllgatherOp);
REGISTER_KERNEL_BUILDER(
    Name("HorovodTpuBroadcast").Device(tensorflow::DEVICE_CPU),
    HvdBroadcastOp);
REGISTER_KERNEL_BUILDER(
    Name("HorovodTpuAlltoall").Device(tensorflow::DEVICE_CPU),
    HvdAlltoallOp);

}  // namespace

extern "C" {

// Registered once at import: `fn` is a Python callable (borrowed ref is
// upgraded to a strong one).
void hvd_tf_set_trampoline(PyObject* fn) {
  PyGILState_STATE st = PyGILState_Ensure();
  Py_XDECREF(g_trampoline);
  g_trampoline = fn;
  Py_XINCREF(g_trampoline);
  PyGILState_Release(st);
}

// Completion path for ONE output of a pending op, called from the
// runtime's executor thread (ctypes releases the GIL around this call,
// so done() may run TF work inline without deadlocking). Allocates
// output `out_index` with the post-negotiation shape and copies `data`
// (nbytes) into it; done() fires when every output of the op has
// finished (single-output ops: immediately). status != 0 fails the op
// with `error` once; remaining members still drain.
void hvd_tf_finish(long long handle, int out_index, int status,
                   const char* error, const void* data,
                   const long long* dims, int ndims, long long nbytes) {
  static const bool debug = std::getenv("HVD_TF_DEBUG") != nullptr;
  if (debug) {
    std::fprintf(stderr,
                 "[hvd_tf_finish] handle=%lld idx=%d status=%d ndims=%d "
                 "nbytes=%lld\n",
                 handle, out_index, status, ndims, nbytes);
  }
  // Phase 1 (locked): record failure or allocate this member's output.
  // The bulk memcpy runs OUTSIDE the lock — completions of different
  // outputs write disjoint buffers, and holding g_mu through a large
  // copy would stall every other dispatch/completion.
  Tensor* out = nullptr;
  {
    std::lock_guard<std::mutex> l(g_mu);
    auto it = g_pending.find(handle);
    if (it == g_pending.end()) return;
    PendingOp& p = it->second;
    if (status != 0) {
      if (!p.failed) {
        p.failed = true;
        p.ctx->CtxFailure(tensorflow::errors::Internal(
            error != nullptr ? error : "horovod_tpu collective failed"));
      }
    } else if (!p.failed) {
      TensorShape shape;
      for (int i = 0; i < ndims; ++i) shape.AddDim(dims[i]);
      tensorflow::Status s = p.ctx->allocate_output(out_index, shape, &out);
      if (!s.ok()) {
        p.failed = true;
        p.ctx->CtxFailure(s);
        out = nullptr;
      }
    }
  }
  if (out != nullptr && nbytes > 0) {
    std::memcpy(const_cast<char*>(out->tensor_data().data()), data,
                static_cast<size_t>(nbytes));
  }
  // Phase 2 (locked): decrement; the entry cannot have been erased in
  // between because only the final decrement erases and ours is pending.
  PendingOp done_op;
  bool fire = false;
  {
    std::lock_guard<std::mutex> l(g_mu);
    auto it = g_pending.find(handle);
    if (it == g_pending.end()) return;
    if (--it->second.remaining <= 0) {
      done_op = std::move(it->second);
      g_pending.erase(it);
      fire = true;
    }
  }
  if (fire) done_op.done();
}

}  // extern "C"
