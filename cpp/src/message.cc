#include "hvd/message.h"

namespace hvd {
namespace wire {

void EncodeRequest(Writer& w, const Request& r) {
  w.I32(r.rank);
  w.U8(static_cast<uint8_t>(r.type));
  w.U8(static_cast<uint8_t>(r.dtype));
  w.I32(r.root_rank);
  w.I32(r.reduce_op);
  w.F64(r.prescale);
  w.F64(r.postscale);
  w.Str(r.name);
  w.I64(r.group_id);
  w.I32(r.group_size);
  w.I32(r.process_set_id);
  w.U32(static_cast<uint32_t>(r.shape.size()));
  for (auto d : r.shape) w.I64(d);
}

bool DecodeRequest(Reader& rd, Request* out) {
  out->rank = rd.I32();
  out->type = static_cast<RequestType>(rd.U8());
  out->dtype = static_cast<DataType>(rd.U8());
  out->root_rank = rd.I32();
  out->reduce_op = rd.I32();
  out->prescale = rd.F64();
  out->postscale = rd.F64();
  out->name = rd.Str();
  out->group_id = rd.I64();
  out->group_size = rd.I32();
  out->process_set_id = rd.I32();
  uint32_t ndim = rd.U32();
  if (ndim > 256) return false;
  out->shape.clear();
  for (uint32_t i = 0; i < ndim; ++i) out->shape.push_back(rd.I64());
  return rd.ok();
}

std::vector<uint8_t> EncodeRequestList(const RequestList& rl) {
  Writer w;
  w.U8(rl.shutdown ? 1 : 0);
  w.Bytes(rl.cache_bits);
  w.U32(static_cast<uint32_t>(rl.requests.size()));
  for (const auto& r : rl.requests) EncodeRequest(w, r);
  return std::move(w.buf);
}

bool DecodeRequestList(const uint8_t* p, size_t n, RequestList* out) {
  Reader rd(p, n);
  out->shutdown = rd.U8() != 0;
  out->cache_bits = rd.Bytes();
  uint32_t count = rd.U32();
  if (count > 1u << 20) return false;
  out->requests.clear();
  out->requests.reserve(count);
  for (uint32_t i = 0; i < count; ++i) {
    Request r;
    if (!DecodeRequest(rd, &r)) return false;
    out->requests.push_back(std::move(r));
  }
  return rd.ok();
}

void EncodeResponse(Writer& w, const Response& r) {
  w.U8(static_cast<uint8_t>(r.type));
  w.U8(static_cast<uint8_t>(r.dtype));
  w.I32(r.root_rank);
  w.I32(r.reduce_op);
  w.F64(r.prescale);
  w.F64(r.postscale);
  w.I64(r.total_bytes);
  w.I32(r.participants);
  w.I64(r.group_id);
  w.I32(r.process_set_id);
  w.Str(r.error);
  w.U32(static_cast<uint32_t>(r.names.size()));
  for (const auto& s : r.names) w.Str(s);
  w.U32(static_cast<uint32_t>(r.entry_shapes.size()));
  for (const auto& shape : r.entry_shapes) {
    w.U32(static_cast<uint32_t>(shape.size()));
    for (auto d : shape) w.I64(d);
  }
  w.U32(static_cast<uint32_t>(r.rank_sizes.size()));
  for (auto s : r.rank_sizes) w.I64(s);
}

bool DecodeResponse(Reader& rd, Response* out) {
  out->type = static_cast<ResponseType>(rd.U8());
  out->dtype = static_cast<DataType>(rd.U8());
  out->root_rank = rd.I32();
  out->reduce_op = rd.I32();
  out->prescale = rd.F64();
  out->postscale = rd.F64();
  out->total_bytes = rd.I64();
  out->participants = rd.I32();
  out->group_id = rd.I64();
  out->process_set_id = rd.I32();
  out->error = rd.Str();
  uint32_t n = rd.U32();
  if (n > 1u << 20) return false;
  out->names.clear();
  out->names.reserve(n);
  for (uint32_t i = 0; i < n; ++i) out->names.push_back(rd.Str());
  uint32_t nshapes = rd.U32();
  if (nshapes > 1u << 20) return false;
  out->entry_shapes.clear();
  out->entry_shapes.reserve(nshapes);
  for (uint32_t i = 0; i < nshapes; ++i) {
    uint32_t ndim = rd.U32();
    if (ndim > 256) return false;
    std::vector<int64_t> shape;
    for (uint32_t j = 0; j < ndim; ++j) shape.push_back(rd.I64());
    out->entry_shapes.push_back(std::move(shape));
  }
  uint32_t nsizes = rd.U32();
  if (nsizes > 1u << 20) return false;
  out->rank_sizes.clear();
  for (uint32_t i = 0; i < nsizes; ++i) out->rank_sizes.push_back(rd.I64());
  return rd.ok();
}

std::vector<uint8_t> EncodeResponseList(const ResponseList& rl) {
  Writer w;
  w.U8(rl.shutdown ? 1 : 0);
  w.F64(rl.cycle_time_ms);
  w.I64(rl.fusion_threshold);
  w.I64(rl.tuned_flags);
  w.U32(static_cast<uint32_t>(rl.responses.size()));
  for (const auto& r : rl.responses) EncodeResponse(w, r);
  return std::move(w.buf);
}

bool DecodeResponseList(const uint8_t* p, size_t n, ResponseList* out) {
  Reader rd(p, n);
  out->shutdown = rd.U8() != 0;
  out->cycle_time_ms = rd.F64();
  out->fusion_threshold = rd.I64();
  out->tuned_flags = static_cast<int32_t>(rd.I64());
  uint32_t count = rd.U32();
  if (count > 1u << 20) return false;
  out->responses.clear();
  out->responses.reserve(count);
  for (uint32_t i = 0; i < count; ++i) {
    Response r;
    if (!DecodeResponse(rd, &r)) return false;
    out->responses.push_back(std::move(r));
  }
  return rd.ok();
}

}  // namespace wire
}  // namespace hvd
