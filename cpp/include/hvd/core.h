// Native control-plane core: tensor table, negotiation, fusion, cache,
// stall detection, timeline, autotune, and the background cycle loop.
//
// Architectural parity with the reference core (horovod/common/operations.cc
// + controller.cc + global_state.h): one background thread per process owns
// all coordination; framework threads are producers into a mutex-guarded
// table. TPU-native difference: this core never touches tensor *data* —
// it emits fused execution Plans that the embedding runtime (JAX) executes
// as XLA collectives, reporting completion back (PlanDone) so the core can
// drive its timeline/autotune/stall machinery.
#pragma once

#include <array>
#include <atomic>
#include <chrono>
#include <condition_variable>
#include <cstdio>
#include <deque>
#include <functional>
#include <list>
#include <map>
#include <mutex>
#include <set>
#include <string>
#include <thread>
#include <unordered_map>
#include <vector>

#include "hvd/common.h"

namespace hvd {

double NowSec();

// ---------------------------------------------------------------- logging
enum class LogLevel : int { kTrace = 0, kDebug, kInfo, kWarn, kError, kFatal };
void LogSetLevel(int level);
void LogSetRank(int rank);
void Log(LogLevel level, const std::string& msg);
#define HVD_LOG(lvl, msg) ::hvd::Log(::hvd::LogLevel::lvl, (msg))

// ---------------------------------------------------------------- timeline
// Chrome-tracing JSON writer with a dedicated writer thread (role parity
// with the reference Timeline; events: negotiation phases, plan execution,
// cycle marks).
class Timeline {
 public:
  void Initialize(const std::string& path, int rank);
  bool initialized() const { return initialized_.load(); }
  void Shutdown();
  void NegotiateStart(const std::string& tensor, const std::string& op);
  void NegotiateRankReady(const std::string& tensor, int rank);
  void NegotiateEnd(const std::string& tensor, const std::string& op);
  void Begin(const std::string& tensor, const std::string& activity);
  // Begin with a plan correlation id: the same "hvd_plan_<id>" string is
  // emitted by the Python executor as a jax.profiler TraceAnnotation, so
  // a slow cycle in this trace can be matched to its on-chip XLA
  // profile (SURVEY §5 timeline<->XLA interop).
  void BeginPlan(const std::string& tensor, const std::string& activity,
                 uint64_t plan_id);
  void End(const std::string& tensor, const std::string& activity);
  void MarkCycle();

 private:
  int Tid(const std::string& tensor);
  void WriterLoop();
  double NowUs();

  std::atomic<bool> initialized_{false};
  std::atomic<bool> stop_{false};
  int rank_ = 0;
  double start_ = 0;
  FILE* file_ = nullptr;
  bool first_event_ = true;
  std::unordered_map<std::string, int> tids_;
  int next_tid_ = 1;
  std::mutex mu_;
  // Serializes whole Initialize/Shutdown sessions against each other
  // (held across the writer join, which mu_ must not be).
  std::mutex session_mu_;
  std::condition_variable cv_;
  std::deque<std::string> queue_;
  std::thread writer_;
};

// ---------------------------------------------------------------- cache
// LRU cache of coordinator verdicts keyed by request signature, so steady-
// state iterations skip the negotiation round (role parity with the
// reference ResponseCache). Multi-process coherence rides the cycle
// protocol: every rank sends hit-bitvectors; the coordinator ANDs them and
// only commonly-hit entries execute from cache.
class ResponseCache {
 public:
  void SetCapacity(size_t cap) { capacity_ = cap; }
  size_t capacity() const { return capacity_; }
  // Returns bit position if cached, -1 otherwise.
  int32_t Lookup(const Request& r) const;
  void Put(const Request& r, const Response& resp);
  bool Get(int32_t bit, Response* out) const;
  // Recover the canonical Request a bit stands for (coordinator side:
  // a cache bit on the wire is a compressed re-announcement).
  bool GetRequest(int32_t bit, Request* out) const;
  void Invalidate(const std::string& name);
  void Clear();
  size_t size() const { return entries_.size(); }
  static std::string Key(const Request& r);

 private:
  struct Entry {
    std::string key;
    Request request;
    Response response;
    uint64_t last_used = 0;
  };
  size_t capacity_ = 1024;
  uint64_t tick_ = 0;
  std::vector<Entry> entries_;                    // bit index -> entry
  std::unordered_map<std::string, int32_t> index_;  // key -> bit
  std::vector<int32_t> free_bits_;
  mutable std::mutex mu_;
};

// ---------------------------------------------------------------- stall
class StallInspector {
 public:
  void Configure(int warn_sec, int shutdown_sec) {
    warn_sec_ = warn_sec;
    shutdown_sec_ = shutdown_sec;
  }
  void Record(const std::string& name, int rank);
  void Clear(const std::string& name);
  void Reset() {
    std::lock_guard<std::mutex> l(mu_);
    pending_.clear();
  }
  // Returns true if shutdown threshold exceeded.
  bool Check(int size);

 private:
  struct Info {
    double first_seen = 0;
    std::set<int> ranks;
    bool warned = false;
  };
  int warn_sec_ = 60;
  int shutdown_sec_ = 0;
  std::map<std::string, Info> pending_;
  std::mutex mu_;
};

// ---------------------------------------------------------------- autotune
// Joint Bayesian optimization of (fusion_threshold, cycle_time) plus the
// categorical knobs (hierarchical_allreduce, hierarchical_allgather,
// cache_enabled), scored by observed data-plane throughput — role parity
// with the reference ParameterManager + optim/ (GP regressor + Expected
// Improvement; the reference's joint categorical tuning is
// parameter_manager.h:42-246). Categoricals embed as {0,1} dimensions of
// the same RBF GP.
class ParameterManager {
 public:
  void Initialize(double cycle_ms, int64_t fusion_bytes, int warmup,
                  int steps_per_sample, const std::string& log_path);
  // Initial categorical values + whether the tuner may explore them
  // (hierarchical dims are only explorable when a (cross, local) grid
  // exists; cache_enabled is always explorable when autotune is on).
  void SetCategorical(bool hier_allreduce, bool hier_allgather,
                      bool cache_enabled, bool tune_hierarchical);
  // Worker-side sync of the rank-0 verdict's flag bitmask (-1 = no-op).
  void ApplyFlags(int flags);
  // Bitmask for the verdict: bit0 hier_allreduce, bit1 hier_allgather,
  // bit2 cache_enabled. Locked: Tune()/ApplyFlags() write concurrently.
  int Flags() const;
  bool cache_enabled() const {
    std::lock_guard<std::mutex> l(mu_);
    return cache_enabled_;
  }
  void SetEnabled(bool e) { enabled_ = e; }
  bool enabled() const { return enabled_; }
  // Record one executed plan (bytes moved). Returns true if params changed.
  bool Update(int64_t bytes, double duration_s);
  double cycle_time_ms() const { return cycle_ms_; }
  int64_t fusion_threshold() const { return fusion_bytes_; }
  bool hierarchical_allreduce() const { return hier_allreduce_; }
  bool hierarchical_allgather() const { return hier_allgather_; }

 private:
  void Tune(double score);
  bool enabled_ = false;
  double cycle_ms_ = 5.0;
  int64_t fusion_bytes_ = 64ll << 20;
  bool hier_allreduce_ = false;
  bool hier_allgather_ = false;
  bool cache_enabled_ = true;
  bool tune_hierarchical_ = false;
  int warmup_remaining_ = 3;
  int steps_per_sample_ = 10;
  int steps_in_sample_ = 0;
  int64_t bytes_in_sample_ = 0;
  double sample_start_ = 0;
  std::vector<double> scores_;  // median-of-samples scoring
  // GP observations: x = (log2 fusion, log2 cycle, hier_ar, hier_ag,
  // cache), y = score.
  std::vector<std::array<double, 5>> xs_;
  std::vector<double> ys_;
  double best_score_ = 0;
  std::array<double, 5> best_x_ = {0, 0, 0, 0, 1};
  std::string log_path_;
  mutable std::mutex mu_;
};

// ---------------------------------------------------------------- plans
// A fused execution unit handed to the embedding runtime.
struct Plan {
  uint64_t id = 0;
  Response response;
  // Autotuned categorical knobs in force when this plan was dispatched
  // (stamped from the delivering verdict so every rank compiles the same
  // lowering); -1 = autotune off, use env-config knobs.
  int32_t tuned_flags = -1;
};

// ---------------------------------------------------------------- transport
// Control-plane transport: rank 0 coordinates over TCP (role parity with
// the reference's Gloo controller + HTTP rendezvous). Lockstep per cycle:
// every worker sends its RequestList, rank 0 replies with the fused
// ResponseList.
class ControlTransport {
 public:
  virtual ~ControlTransport() = default;
  virtual Status Init(const CoreConfig& cfg) = 0;
  // Rank 0: gather each rank's RequestList (index 0 = self, passed in).
  virtual Status Gather(const RequestList& mine,
                        std::vector<RequestList>* all) = 0;
  // Rank 0: broadcast the verdict; workers: exchange (send mine, recv out).
  virtual Status Broadcast(const ResponseList& rl) = 0;
  virtual Status Exchange(const RequestList& mine, ResponseList* out) = 0;
  virtual void Close() = 0;
};

ControlTransport* NewTcpTransport();

// ---------------------------------------------------------------- core
class Core {
 public:
  static Core& Get();

  Status Init(const CoreConfig& cfg);
  void Shutdown();
  bool initialized() const { return initialized_.load(); }
  const CoreConfig& config() const { return cfg_; }

  // Producer API (any thread). Returns ticket id (>0) or 0 on duplicate.
  Status Enqueue(const Request& req, uint64_t* ticket);
  Status EnqueueJoin(uint64_t* ticket);

  // Latency hint from a synchronously-waiting producer: everything this
  // caller will submit is already queued, so the next cycle may seal
  // immediately instead of holding the fusion grace/linger for
  // companions that are not coming.
  void FlushHint();

  // Process sets (later-reference horovod.ProcessSet parity): register a
  // rank subset under a nonzero id. MUST be called identically on every
  // rank before any collective uses the id (the Python layer enforces
  // this with a registration barrier); the coordinator counts readiness
  // against the membership and non-member ranks never see the plans.
  Status RegisterProcessSet(int32_t id, const std::vector<int32_t>& ranks);
  Status RemoveProcessSet(int32_t id);

  // Executor API: block up to timeout for the next plan. Returns 1 when a
  // plan was produced, 0 on timeout, -1 on shutdown.
  int NextPlan(Plan* out, int timeout_ms);
  void PlanDone(uint64_t plan_id, int status_code, const std::string& error,
                double duration_s, int64_t bytes);

  // Ticket status polling: 0 in-progress, 1 ok, <0 error code.
  int TicketStatus(uint64_t ticket, std::string* error);

  double cycle_time_ms() const { return params_.cycle_time_ms(); }
  bool eager_wakeup() const { return eager_wakeup_; }
  long long grouped_splits() const { return grouped_splits_.load(); }
  int64_t fusion_threshold() const { return params_.fusion_threshold(); }
  int tuned_flags() const { return params_.Flags(); }

  Timeline& timeline() { return timeline_; }
  // Runtime timeline control (later-reference hvd.start_timeline /
  // stop_timeline): start/stop the catapult writer while training runs.
  Status StartTimeline(const std::string& path, bool mark_cycles);
  void StopTimeline();
  size_t cache_size() const { return cache_.size(); }

 private:
  Core() = default;
  void BackgroundLoop();
  void RunCycleOnce();
  // Coordinator-side: decide ready tensors, validate, fuse.
  ResponseList Coordinate(std::vector<RequestList>& lists);
  void FuseAndEmit(std::vector<Request>& ready, ResponseList* out,
                   const std::map<int32_t, std::vector<int32_t>>& ps_snap);
  void DispatchResponses(const ResponseList& rl);
  void FailAll(const Status& s);

  CoreConfig cfg_;
  std::atomic<bool> initialized_{false};
  std::atomic<bool> shutdown_{false};
  std::thread thread_;

  // Pending tensor table (metadata only; payloads live in Python).
  struct Pending {
    Request request;
    uint64_t ticket;
  };
  std::mutex table_mu_;
  std::map<std::string, Pending> table_;
  std::vector<Request> queued_;
  std::condition_variable wake_cv_;
  bool wake_ = false;
  bool flush_hint_ = false;        // guarded by table_mu_
  // Groups that could not fuse into a single response (heterogeneous
  // member signatures): observability for grouped_allreduce.
  std::atomic<long long> grouped_splits_{0};
  bool eager_wakeup_ = true;
  double linger_s_ = 0.0;
  double last_enqueue_ = 0.0;      // guarded by table_mu_
  // Burst history for the adaptive linger; starts at 2 ("assume burst")
  // so the cold-start cycle keeps the full fusion window — only observed
  // solo traffic enables the fast seal. Guarded by table_mu_.
  size_t last_cycle_nreq_ = 2;
  bool joined_ = false;
  uint64_t join_ticket_ = 0;

  // Coordinator state (rank 0): per-tensor readiness counting.
  struct Negotiation {
    Request request;
    std::set<int32_t> ranks;
    bool error = false;
    std::string error_msg;
    // Allgather: per-rank first-dimension sizes (displacement math,
    // reference MPI_Allgatherv mpi_operations.cc:83-162).
    std::map<int32_t, int64_t> dim0;
  };
  // First-class grouped collectives (coordinator state, rank 0 only):
  // members of a group are held here once all-ranks-ready until every
  // group_size member arrives, then emitted in one cycle (and fused into
  // one response per signature, exempt from the fusion threshold). A
  // member that fails validation poisons the whole group.
  std::map<int64_t, std::set<std::string>> group_ready_;
  // gid -> (error message, members still expected to arrive and fail)
  std::map<int64_t, std::pair<std::string, int>> group_poisoned_;
  std::map<std::string, Negotiation> negotiating_;
  std::set<int32_t> joined_ranks_;

  // Registered process sets: id -> sorted member ranks. Guarded by
  // ps_mu_ (written from the API thread at registration, read by the
  // background thread during negotiation/dispatch). Set 0 is implicit
  // (all ranks) and never stored.
  std::mutex ps_mu_;
  std::map<int32_t, std::vector<int32_t>> process_sets_;
  // Lock-order-free snapshot helper (copy under ps_mu_). The coordinator
  // hot path instead snapshots the WHOLE registry once per cycle
  // (Coordinate) and never touches ps_mu_ per tensor.
  bool LookupProcessSet(int32_t id, std::vector<int32_t>* ranks);
  // Copy-free membership probe for the per-op Enqueue/Dispatch paths.
  // known=false when the id is not registered.
  bool IsProcessSetMember(int32_t id, int32_t rank, bool* known);

  // Plan queue to the executor. Tickets are captured at dispatch time so
  // completion never resolves through names (a same-name tensor can be
  // legally re-enqueued while its predecessor's plan is still executing).
  struct Inflight {
    Response response;
    std::vector<uint64_t> tickets;
  };
  std::mutex plan_mu_;
  std::condition_variable plan_cv_;
  std::deque<Plan> plans_;
  uint64_t next_plan_id_ = 1;
  std::unordered_map<uint64_t, Inflight> inflight_;

  // Ticket results.
  std::mutex ticket_mu_;
  std::condition_variable ticket_cv_;
  uint64_t next_ticket_ = 1;
  std::unordered_map<uint64_t, std::pair<int, std::string>> tickets_;

  ResponseCache cache_;
  StallInspector stall_;
  ParameterManager params_;
  Timeline timeline_;
  std::atomic<bool> timeline_mark_cycles_{true};
  ControlTransport* transport_ = nullptr;
};

}  // namespace hvd
