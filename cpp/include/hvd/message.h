// Compact binary wire format for the control plane.
//
// Role parity with the reference's FlatBuffers-based message layer
// (horovod/common/wire/message.fbs, message.cc) — re-designed as a plain
// length-prefixed little-endian encoding: the control messages are tiny
// (names + shapes), exchanged once per cycle, and a zero-dependency codec
// keeps the native core self-contained.
#pragma once

#include <cstring>
#include <string>
#include <vector>

#include "hvd/common.h"

namespace hvd {
namespace wire {

class Writer {
 public:
  std::vector<uint8_t> buf;
  void U8(uint8_t v) { buf.push_back(v); }
  void I32(int32_t v) { Raw(&v, 4); }
  void U32(uint32_t v) { Raw(&v, 4); }
  void I64(int64_t v) { Raw(&v, 8); }
  void F64(double v) { Raw(&v, 8); }
  void Str(const std::string& s) {
    U32(static_cast<uint32_t>(s.size()));
    Raw(s.data(), s.size());
  }
  void Bytes(const std::vector<uint8_t>& b) {
    U32(static_cast<uint32_t>(b.size()));
    Raw(b.data(), b.size());
  }
  void Raw(const void* p, size_t n) {
    const uint8_t* c = static_cast<const uint8_t*>(p);
    buf.insert(buf.end(), c, c + n);
  }
};

class Reader {
 public:
  Reader(const uint8_t* p, size_t n) : p_(p), end_(p + n) {}
  bool ok() const { return ok_; }
  uint8_t U8() { uint8_t v = 0; Get(&v, 1); return v; }
  int32_t I32() { int32_t v = 0; Get(&v, 4); return v; }
  uint32_t U32() { uint32_t v = 0; Get(&v, 4); return v; }
  int64_t I64() { int64_t v = 0; Get(&v, 8); return v; }
  double F64() { double v = 0; Get(&v, 8); return v; }
  std::string Str() {
    uint32_t n = U32();
    if (!Check(n)) return {};
    std::string s(reinterpret_cast<const char*>(p_), n);
    p_ += n;
    return s;
  }
  std::vector<uint8_t> Bytes() {
    uint32_t n = U32();
    if (!Check(n)) return {};
    std::vector<uint8_t> b(p_, p_ + n);
    p_ += n;
    return b;
  }

 private:
  bool Check(size_t n) {
    if (static_cast<size_t>(end_ - p_) < n) { ok_ = false; return false; }
    return true;
  }
  void Get(void* out, size_t n) {
    if (!Check(n)) return;
    std::memcpy(out, p_, n);
    p_ += n;
  }
  const uint8_t* p_;
  const uint8_t* end_;
  bool ok_ = true;
};

void EncodeRequest(Writer& w, const Request& r);
bool DecodeRequest(Reader& rd, Request* out);
std::vector<uint8_t> EncodeRequestList(const RequestList& rl);
bool DecodeRequestList(const uint8_t* p, size_t n, RequestList* out);
void EncodeResponse(Writer& w, const Response& r);
bool DecodeResponse(Reader& rd, Response* out);
std::vector<uint8_t> EncodeResponseList(const ResponseList& rl);
bool DecodeResponseList(const uint8_t* p, size_t n, ResponseList* out);

}  // namespace wire
}  // namespace hvd
