// Core types for the native control-plane runtime.
//
// TPU-native re-design of the reference's horovod/common/common.h. The
// native core owns *metadata and coordination only*: tensor payloads stay in
// the Python/XLA world (device HBM), so the types here carry names, shapes
// and dtypes — never data pointers. The data plane is executed by the
// embedding runtime (JAX) from plans this core emits.
#pragma once

#include <cstdint>
#include <string>
#include <vector>

namespace hvd {

enum class StatusCode : int32_t {
  kOk = 0,
  kUnknownError = 1,
  kPreconditionError = 2,
  kAborted = 3,
  kInvalidArgument = 4,
  kInProgress = 5,
};

struct Status {
  StatusCode code = StatusCode::kOk;
  std::string reason;
  bool ok() const { return code == StatusCode::kOk; }
  static Status OK() { return {}; }
  static Status Error(StatusCode c, std::string r) { return {c, std::move(r)}; }
};

// Wire dtype ids — aligned with horovod_tpu.common.types.DataType (Python).
enum class DataType : uint8_t {
  kUint8 = 0, kInt8 = 1, kUint16 = 2, kInt16 = 3, kInt32 = 4, kInt64 = 5,
  kFloat16 = 6, kFloat32 = 7, kFloat64 = 8, kBool = 9, kBfloat16 = 10,
  kComplex64 = 11,
};

inline int64_t DataTypeSize(DataType d) {
  switch (d) {
    case DataType::kUint8: case DataType::kInt8: case DataType::kBool: return 1;
    case DataType::kUint16: case DataType::kInt16: case DataType::kFloat16:
    case DataType::kBfloat16: return 2;
    case DataType::kInt32: case DataType::kFloat32: return 4;
    case DataType::kInt64: case DataType::kFloat64: case DataType::kComplex64:
      return 8;
  }
  return 4;
}

enum class RequestType : uint8_t {
  kAllreduce = 0, kAllgather = 1, kBroadcast = 2, kJoin = 3, kAlltoall = 4,
  kReducescatter = 5, kAdasum = 6,
};

enum class ResponseType : uint8_t {
  kAllreduce = 0, kAllgather = 1, kBroadcast = 2, kJoin = 3, kAlltoall = 4,
  kReducescatter = 5, kAdasum = 6, kError = 7,
};

enum class ReduceOp : int32_t {
  kAverage = 1, kSum = 2, kAdasum = 3, kMin = 4, kMax = 5, kProduct = 6,
};

// Readiness announcement for one named tensor on one rank (the analogue of
// the reference's Request message; shape/dtype travel so the coordinator
// can validate cross-rank consistency).
struct Request {
  int32_t rank = 0;
  RequestType type = RequestType::kAllreduce;
  DataType dtype = DataType::kFloat32;
  int32_t root_rank = -1;
  int32_t reduce_op = static_cast<int32_t>(ReduceOp::kSum);
  double prescale = 1.0;
  double postscale = 1.0;
  std::string name;
  std::vector<int64_t> shape;
  // First-class grouped collectives (grouped_allreduce): nonzero id ties
  // members together; the coordinator holds the group until all
  // group_size members are ready on every rank and fuses them into one
  // response regardless of cycle boundaries or the fusion threshold.
  int64_t group_id = 0;
  int32_t group_size = 0;
  // Process set this collective runs over (later-reference API parity:
  // horovod.ProcessSet). 0 = the global set; other ids must be registered
  // identically on every rank via Core::RegisterProcessSet before use.
  // Readiness is counted against the set's membership and the emitted
  // plan executes on a sub-mesh of the member ranks' devices only.
  int32_t process_set_id = 0;

  int64_t NumElements() const {
    int64_t n = 1;
    for (auto d : shape) n *= d;
    return n;
  }
  int64_t ByteSize() const { return NumElements() * DataTypeSize(dtype); }
};

struct RequestList {
  std::vector<Request> requests;
  bool shutdown = false;
  // Cache-hit bitvector for this cycle (response-cache coordination).
  std::vector<uint8_t> cache_bits;
};

// Coordinator verdict: a fused group of tensors to execute together.
struct Response {
  ResponseType type = ResponseType::kAllreduce;
  std::vector<std::string> names;
  std::string error;
  DataType dtype = DataType::kFloat32;
  int32_t root_rank = -1;
  int32_t reduce_op = static_cast<int32_t>(ReduceOp::kSum);
  double prescale = 1.0;
  double postscale = 1.0;
  int64_t total_bytes = 0;
  // Canonical per-entry shapes (coordinator-validated), so a Joined rank
  // can substitute zero tensors it never submitted (reference join
  // semantics: joined ranks participate with zeros).
  std::vector<std::vector<int64_t>> entry_shapes;
  // Allgather: first-dimension size per rank (displacement math).
  std::vector<int64_t> rank_sizes;
  // Number of ranks contributing real (non-zero-substituted) tensors —
  // the correct Average divisor under Join.
  int32_t participants = 0;
  // Nonzero for grouped responses (kept out of the response cache: the
  // cache-bit path cannot carry group membership).
  int64_t group_id = 0;
  // Process set this plan executes over (0 = global). Non-member ranks
  // never see the plan (DispatchResponses skips it for them).
  int32_t process_set_id = 0;
};

struct ResponseList {
  std::vector<Response> responses;
  bool shutdown = false;
  // Autotuned knobs broadcast from rank 0 (parameter manager sync).
  double cycle_time_ms = 0.0;      // 0 = unchanged
  int64_t fusion_threshold = 0;    // 0 = unchanged
  // Categorical knobs: bit0 hierarchical_allreduce, bit1
  // hierarchical_allgather, bit2 cache_enabled; -1 = unchanged.
  int32_t tuned_flags = -1;
};

struct CoreConfig {
  int32_t rank = 0;
  int32_t size = 1;
  int32_t local_rank = 0;
  int32_t local_size = 1;
  int32_t cross_rank = 0;
  int32_t cross_size = 1;
  double cycle_time_ms = 5.0;
  int64_t fusion_threshold = 64ll << 20;
  int32_t cache_capacity = 1024;
  int32_t stall_warning_sec = 60;
  int32_t stall_shutdown_sec = 0;
  int32_t autotune = 0;
  int32_t autotune_warmup_samples = 3;
  int32_t autotune_steps_per_sample = 10;
  // Initial categorical knob values (env: HOROVOD_HIERARCHICAL_*).
  int32_t hierarchical_allreduce = 0;
  int32_t hierarchical_allgather = 0;
  int32_t log_level = 2;  // 0=trace 1=debug 2=info 3=warn 4=error
  char timeline_path[1024] = {0};
  char coord_addr[256] = {0};  // empty => single-process controller
  int32_t coord_port = 0;
  char autotune_log[1024] = {0};
};

}  // namespace hvd
